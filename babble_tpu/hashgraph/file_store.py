"""Persistent write-through store: the BadgerStore analog on sqlite3.

Reference hashgraph/badger_store.go:28-386. Layering matches the
reference: an InmemStore is the hot cache; every write also lands in
the database; reads fall back to the database when the cache misses
(LRU eviction / fresh restart). The topologically-keyed event log
(`topo_%09d` keys there, an autoincrement rowid-ordered table here)
feeds `Hashgraph.bootstrap()` replay.

sqlite3 is the idiomatic stand-in for the embedded Badger KV store: in
the standard library, single-file, crash-safe."""

from __future__ import annotations

import json
import os
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Tuple

from ..common import StoreError, StoreErrType
from .block import Block
from .event import Event, event_from_json_obj
from .inmem_store import InmemStore
from .root import Root, new_base_root
from .round_info import RoundInfo, RoundEvent, Trilean


def _round_to_json(info: RoundInfo) -> str:
    return json.dumps(
        {
            "Events": {
                x: {"Witness": e.witness, "Famous": int(e.famous)}
                for x, e in info.events.items()
            }
        }
    )


def _round_from_json(data: str) -> RoundInfo:
    obj = json.loads(data)
    info = RoundInfo()
    for x, e in (obj.get("Events") or {}).items():
        info.events[x] = RoundEvent(
            witness=e["Witness"], famous=Trilean(e["Famous"])
        )
    return info


class FileStore:
    """20-method Store (hashgraph/store.go:3-25) with durability."""

    def __init__(
        self,
        participants: Dict[str, int],
        cache_size: int,
        path: str,
        create: bool = True,
    ):
        self.path = path
        self._lock = threading.RLock()
        exists = os.path.exists(path)
        if not exists and not create:
            raise StoreError(StoreErrType.KEY_NOT_FOUND, path)
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._init_schema()

        if exists and create:
            # A populated database must be reopened with load(): the
            # create path would overwrite persisted roots with fresh
            # base roots while leaving the events table — an empty
            # cache over a non-empty log whose last_from/known disagree
            # with disk until a bootstrap replay.
            row = self._db.execute("SELECT COUNT(*) FROM events").fetchone()
            if row and row[0]:
                self._db.close()
                raise ValueError(
                    f"{path} already contains events; use FileStore.load()"
                )
        if exists and not create:
            participants = self._db_participants()
        elif participants:
            self._db_set_participants(participants)
        self.inmem = InmemStore(participants, cache_size)
        self._participants = participants

    @classmethod
    def load(cls, cache_size: int, path: str) -> "FileStore":
        """Reopen an existing store, reading participants from disk —
        reference LoadBadgerStore (badger_store.go:54-83)."""
        return cls({}, cache_size, path, create=False)

    def _init_schema(self) -> None:
        with self._lock:
            self._db.executescript(
                """
                CREATE TABLE IF NOT EXISTS events (
                    seq INTEGER PRIMARY KEY AUTOINCREMENT,
                    hex TEXT UNIQUE NOT NULL,
                    creator TEXT NOT NULL,
                    idx INTEGER NOT NULL,
                    topo INTEGER NOT NULL,
                    data TEXT NOT NULL
                );
                CREATE INDEX IF NOT EXISTS events_by_participant
                    ON events (creator, idx);
                CREATE TABLE IF NOT EXISTS rounds (
                    idx INTEGER PRIMARY KEY, data TEXT NOT NULL);
                CREATE TABLE IF NOT EXISTS blocks (
                    rr INTEGER PRIMARY KEY, data TEXT NOT NULL);
                CREATE TABLE IF NOT EXISTS participants (
                    pubkey TEXT PRIMARY KEY, id INTEGER NOT NULL);
                CREATE TABLE IF NOT EXISTS roots (
                    pubkey TEXT PRIMARY KEY, data TEXT NOT NULL);
                """
            )
            self._db.commit()

    # -- participants / roots ---------------------------------------------

    def _db_set_participants(self, participants: Dict[str, int]) -> None:
        with self._lock:
            self._db.executemany(
                "INSERT OR REPLACE INTO participants VALUES (?, ?)",
                list(participants.items()),
            )
            self._db.executemany(
                "INSERT OR REPLACE INTO roots VALUES (?, ?)",
                [
                    (pk, json.dumps(new_base_root().to_dict()))
                    for pk in participants
                ],
            )
            self._db.commit()

    def _db_participants(self) -> Dict[str, int]:
        with self._lock:
            rows = self._db.execute("SELECT pubkey, id FROM participants").fetchall()
        return {pk: pid for pk, pid in rows}

    # -- Store interface ---------------------------------------------------

    def cache_size(self) -> int:
        return self.inmem.cache_size()

    def participants(self) -> Dict[str, int]:
        return self._participants

    def get_event(self, key: str) -> Event:
        try:
            return self.inmem.get_event(key)
        except StoreError:
            pass
        with self._lock:
            row = self._db.execute(
                "SELECT data, topo FROM events WHERE hex = ?", (key,)
            ).fetchone()
        if row is None:
            raise StoreError(StoreErrType.KEY_NOT_FOUND, key)
        ev = event_from_json_obj(json.loads(row[0]))
        ev.topological_index = row[1]
        return ev

    def has_event(self, key: str) -> bool:
        if self.inmem.has_event(key):
            return True
        with self._lock:
            row = self._db.execute(
                "SELECT 1 FROM events WHERE hex = ?", (key,)
            ).fetchone()
        return row is not None

    def set_event(self, event: Event) -> None:
        self.inmem.set_event(event)
        obj = json.loads(event.marshal())
        with self._lock:
            # Replay order is the autoincrement seq (stable across
            # Reset, which restarts topological_index at 0); the topo
            # column preserves the engine-assigned index for reload.
            # Coordinate back-propagation re-calls set_event on old
            # events whose marshaled bytes never change, so conflicts
            # only refresh topo.
            self._db.execute(
                "INSERT INTO events (hex, creator, idx, topo, data) "
                "VALUES (?, ?, ?, ?, ?) "
                "ON CONFLICT(hex) DO UPDATE SET topo = excluded.topo",
                (
                    event.hex(),
                    event.creator(),
                    event.index(),
                    event.topological_index,
                    json.dumps(obj),
                ),
            )
            self._db.commit()

    def participant_events(self, participant: str, skip: int) -> List[str]:
        try:
            res = self.inmem.participant_events(participant, skip)
            # A freshly loaded store's rolling window is empty and
            # returns [] without error; distinguish "synced empty"
            # (participant known in the window) from "window knows
            # nothing" (is_root) and serve the latter from the db.
            if res:
                return res
            _, is_root = self.inmem.last_from(participant)
            if not is_root:
                return res
        except StoreError:
            pass
        with self._lock:
            rows = self._db.execute(
                "SELECT hex FROM events WHERE creator = ? AND idx > ? "
                "ORDER BY idx",
                (participant, skip),
            ).fetchall()
        return [r[0] for r in rows]

    def participant_window(self, participant: str):
        # Live hot-cache window; coordinates that aged out of it fall
        # back to the per-event probe below, which serves from sqlite.
        return self.inmem.participant_window(participant)

    def participant_event_objects(self, participant: str, skip: int) -> List[Event]:
        try:
            res = self.inmem.participant_event_objects(participant, skip)
            # Same freshly-loaded disambiguation as participant_events:
            # an empty window is only authoritative when the participant
            # has genuinely no events past `skip`.
            if res:
                return res
            _, is_root = self.inmem.last_from(participant)
            if not is_root:
                return res
        except StoreError:
            pass
        with self._lock:
            rows = self._db.execute(
                "SELECT data, topo FROM events WHERE creator = ? AND idx > ? "
                "ORDER BY idx",
                (participant, skip),
            ).fetchall()
        out = []
        for data, topo in rows:
            ev = event_from_json_obj(json.loads(data))
            ev.topological_index = topo
            out.append(ev)
        return out

    def participant_event(self, participant: str, index: int) -> str:
        try:
            return self.inmem.participant_event(participant, index)
        except StoreError:
            with self._lock:
                row = self._db.execute(
                    "SELECT hex FROM events WHERE creator = ? AND idx = ?",
                    (participant, index),
                ).fetchone()
            if row is None:
                raise StoreError(StoreErrType.KEY_NOT_FOUND, participant)
            return row[0]

    def last_from(self, participant: str) -> Tuple[str, bool]:
        return self.inmem.last_from(participant)

    def known(self) -> Dict[int, int]:
        return self.inmem.known()

    def consensus_events(self) -> List[str]:
        return self.inmem.consensus_events()

    def consensus_events_count(self) -> int:
        return self.inmem.consensus_events_count()

    def add_consensus_event(self, key: str) -> None:
        self.inmem.add_consensus_event(key)

    def get_round(self, r: int) -> RoundInfo:
        try:
            return self.inmem.get_round(r)
        except StoreError:
            pass
        with self._lock:
            row = self._db.execute(
                "SELECT data FROM rounds WHERE idx = ?", (r,)
            ).fetchone()
        if row is None:
            raise StoreError(StoreErrType.KEY_NOT_FOUND, str(r))
        return _round_from_json(row[0])

    def set_round(self, r: int, round_info: RoundInfo) -> None:
        self.inmem.set_round(r, round_info)
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO rounds VALUES (?, ?)",
                (r, _round_to_json(round_info)),
            )
            self._db.commit()

    def last_round(self) -> int:
        lr = self.inmem.last_round()
        if lr >= 0:
            return lr
        with self._lock:
            row = self._db.execute("SELECT MAX(idx) FROM rounds").fetchone()
        return row[0] if row and row[0] is not None else -1

    def round_witnesses(self, r: int) -> List[str]:
        try:
            return self.get_round(r).witnesses()
        except StoreError:
            return []

    def round_events(self, r: int) -> int:
        try:
            return len(self.get_round(r).events)
        except StoreError:
            return 0

    def get_root(self, participant: str) -> Root:
        try:
            return self.inmem.get_root(participant)
        except StoreError:
            pass
        with self._lock:
            row = self._db.execute(
                "SELECT data FROM roots WHERE pubkey = ?", (participant,)
            ).fetchone()
        if row is None:
            raise StoreError(StoreErrType.NO_ROOT, participant)
        return Root.from_dict(json.loads(row[0]))

    def get_block(self, rr: int) -> Block:
        try:
            return self.inmem.get_block(rr)
        except StoreError:
            pass
        with self._lock:
            row = self._db.execute(
                "SELECT data FROM blocks WHERE rr = ?", (rr,)
            ).fetchone()
        if row is None:
            raise StoreError(StoreErrType.KEY_NOT_FOUND, str(rr))
        return Block.from_json_obj(json.loads(row[0]))

    def set_block(self, block: Block) -> None:
        self.inmem.set_block(block)
        data = json.dumps(block.to_json_obj())
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO blocks VALUES (?, ?)",
                (block.round_received, data),
            )
            self._db.commit()

    def reset(self, roots: Dict[str, Root]) -> None:
        self.inmem.reset(roots)
        with self._lock:
            self._db.executemany(
                "INSERT OR REPLACE INTO roots VALUES (?, ?)",
                [(pk, json.dumps(r.to_dict())) for pk, r in roots.items()],
            )
            self._db.commit()

    def close(self) -> None:
        with self._lock:
            self._db.commit()
            self._db.close()

    # -- bootstrap feed ----------------------------------------------------

    def db_topological_events(self) -> Iterator[Event]:
        """Replay the event log in insertion order — reference
        dbTopologicalEvents (badger_store.go:345-386). Consumed by
        Hashgraph.bootstrap()."""
        with self._lock:
            rows = self._db.execute(
                "SELECT data, topo FROM events ORDER BY seq"
            ).fetchall()
        for data, topo in rows:
            ev = event_from_json_obj(json.loads(data))
            ev.topological_index = topo
            yield ev
