"""Persistent write-through store: the BadgerStore analog on sqlite3.

Reference hashgraph/badger_store.go:28-386. Layering matches the
reference: an InmemStore is the hot cache; every write also lands in
the database; reads fall back to the database when the cache misses
(LRU eviction / fresh restart). The topologically-keyed event log
(`topo_%09d` keys there, an autoincrement rowid-ordered table here)
feeds `Hashgraph.bootstrap()` replay.

sqlite3 is the idiomatic stand-in for the embedded Badger KV store: in
the standard library, single-file, crash-safe.

Crash consistency (docs/robustness.md "Crash recovery"): the database
runs in WAL mode and writes are grouped into explicit transactions via
the Store batch seam (`begin_batch`/`commit_batch`/`rollback_batch`).
One sync batch's event inserts, and one consensus pass's round/witness/
block writes, each land atomically — a process killed at any
instruction leaves either all of a batch or none of it visible after
reload. A `meta` table carries the schema version, the durable
delivered-block anchor (`last_committed_block`, exactly-once app
delivery across restarts) and the consensus anchor (the highest round
written by a COMPLETE consensus pass; rounds above it found at load
time are a torn tail from a pre-transactional writer and are
discarded)."""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from ..common import StoreError, StoreErrType, is_store_err
from ..gojson import Timestamp, ZERO_TIME
from .block import Block
from .event import Event, EventCoordinates, event_from_json_obj
from .inmem_store import InmemStore
from .root import Root, new_base_root
from .round_info import RoundInfo, RoundEvent, Trilean

SCHEMA_VERSION = 2

# store_sync policy -> sqlite synchronous level. In WAL mode:
#   always: fsync the WAL on every commit (survives power loss);
#   batch:  fsync only at WAL checkpoints (survives process kill —
#           commits are atomic either way, WAL frames are checksummed);
#   off:    no fsyncs at all (fastest; still atomic under kill -9
#           because the OS page cache survives the process).
_SYNC_PRAGMA = {"always": "FULL", "batch": "NORMAL", "off": "OFF"}


def _annotations_to_json(ev: Event) -> str:
    """Runtime annotations that are NOT part of the canonical Go-JSON
    event bytes (unexported in the reference): wire coordinates, the
    per-participant ancestry vectors, and consensus marks. Without
    them an event served from the sqlite fallback after LRU eviction
    is unusable as a parent (empty last_ancestors crashes coordinate
    init) and silently breaks strongly_see (zip over an empty vector
    counts zero)."""
    return json.dumps({
        "w": [ev.body.self_parent_index, ev.body.other_parent_creator_id,
              ev.body.other_parent_index, ev.body.creator_id],
        "la": [[c.index, c.hash] for c in ev.last_ancestors],
        "fd": [[c.index, c.hash] for c in ev.first_descendants],
        "rr": ev.round_received,
        "cts": ev.consensus_timestamp.ns,
    })


def _annotations_from_json(ev: Event, data: Optional[str]) -> Event:
    if not data:
        return ev  # legacy row (pre-annotation schema)
    obj = json.loads(data)
    w = obj.get("w")
    if w:
        ev.set_wire_info(w[0], w[1], w[2], w[3])
    ev.last_ancestors = [
        EventCoordinates(hash=h, index=i) for i, h in obj.get("la", [])]
    ev.first_descendants = [
        EventCoordinates(hash=h, index=i) for i, h in obj.get("fd", [])]
    ev.round_received = obj.get("rr")
    cts = obj.get("cts")
    if cts is not None and cts != ZERO_TIME.ns:
        ev.consensus_timestamp = Timestamp(cts)
    return ev


def _round_to_json(info: RoundInfo) -> str:
    return json.dumps(
        {
            "Events": {
                x: {"Witness": e.witness, "Famous": int(e.famous)}
                for x, e in info.events.items()
            }
        }
    )


def _round_from_json(data: str) -> RoundInfo:
    obj = json.loads(data)
    info = RoundInfo()
    for x, e in (obj.get("Events") or {}).items():
        info.events[x] = RoundEvent(
            witness=e["Witness"], famous=Trilean(e["Famous"])
        )
    return info


class FileStore:
    """20-method Store (hashgraph/store.go:3-25) with durability."""

    def __init__(
        self,
        participants: Dict[str, int],
        cache_size: int,
        path: str,
        create: bool = True,
        sync: str = "batch",
    ):
        if sync not in _SYNC_PRAGMA:
            raise ValueError(f"unknown store_sync policy {sync!r}")
        self.path = path
        self.sync = sync
        self._lock = threading.RLock()
        self._closed = False
        # Batch protocol state: while depth > 0 per-statement commits
        # are suppressed and every write joins one sqlite transaction,
        # committed (or rolled back) at the outermost commit_batch.
        self._batch_depth = 0
        self._rounds_dirty = False
        # Durable-commit observability (fsync proxy: wall time of the
        # sqlite COMMIT, which is where the WAL write+fsync happens).
        self.fsync_count = 0
        self.fsync_total_ns = 0
        self.fsync_last_ns = 0
        from ..telemetry import get_registry

        _reg = get_registry()
        self._m_fsync = _reg.histogram(
            "babble_store_fsync_seconds",
            "Store batch-commit wall seconds (WAL write + fsync)",
            sync=sync)
        self._m_fsyncs = _reg.counter(
            "babble_store_fsyncs_total",
            "Store batch commits (WAL write + fsync)", sync=sync)
        exists = os.path.exists(path)
        if not exists and not create:
            raise StoreError(StoreErrType.KEY_NOT_FOUND, path)
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute(f"PRAGMA synchronous={_SYNC_PRAGMA[sync]}")
        legacy = exists and not self._has_meta_table()
        self._init_schema()

        if exists and create:
            # A populated database must be reopened with load(): the
            # create path would overwrite persisted roots with fresh
            # base roots while leaving the events table — an empty
            # cache over a non-empty log whose last_from/known disagree
            # with disk until a bootstrap replay.
            row = self._db.execute("SELECT COUNT(*) FROM events").fetchone()
            if row and row[0]:
                self._db.close()
                raise ValueError(
                    f"{path} already contains events; use FileStore.load()"
                )
        if exists and not create:
            participants = self._db_participants()
            self._recover(legacy)
        elif participants:
            self._db_set_participants(participants)
        self.inmem = InmemStore(participants, cache_size)
        self.inmem.set_last_committed_block(
            self._get_meta_int("last_committed_block", -1))
        self._participants = participants

    @classmethod
    def load(cls, cache_size: int, path: str, sync: str = "batch") -> "FileStore":
        """Reopen an existing store, reading participants from disk —
        reference LoadBadgerStore (badger_store.go:54-83)."""
        return cls({}, cache_size, path, create=False, sync=sync)

    def _has_meta_table(self) -> bool:
        row = self._db.execute(
            "SELECT 1 FROM sqlite_master WHERE type='table' AND name='meta'"
        ).fetchone()
        return row is not None

    def _init_schema(self) -> None:
        with self._lock:
            self._db.executescript(
                """
                CREATE TABLE IF NOT EXISTS events (
                    seq INTEGER PRIMARY KEY AUTOINCREMENT,
                    hex TEXT UNIQUE NOT NULL,
                    creator TEXT NOT NULL,
                    idx INTEGER NOT NULL,
                    topo INTEGER NOT NULL,
                    data TEXT NOT NULL,
                    annotations TEXT
                );
                CREATE INDEX IF NOT EXISTS events_by_participant
                    ON events (creator, idx);
                CREATE TABLE IF NOT EXISTS rounds (
                    idx INTEGER PRIMARY KEY, data TEXT NOT NULL);
                CREATE TABLE IF NOT EXISTS blocks (
                    rr INTEGER PRIMARY KEY, data TEXT NOT NULL);
                CREATE TABLE IF NOT EXISTS participants (
                    pubkey TEXT PRIMARY KEY, id INTEGER NOT NULL);
                CREATE TABLE IF NOT EXISTS roots (
                    pubkey TEXT PRIMARY KEY, data TEXT NOT NULL);
                CREATE TABLE IF NOT EXISTS meta (
                    key TEXT PRIMARY KEY, value TEXT NOT NULL);
                CREATE TABLE IF NOT EXISTS forks (
                    creator TEXT NOT NULL,
                    idx INTEGER NOT NULL,
                    forged TEXT NOT NULL,
                    data TEXT NOT NULL,
                    PRIMARY KEY (creator, idx, forged));
                """
            )
            # Schema-v1 migration: the events table predates the
            # annotations column (CREATE IF NOT EXISTS won't add it).
            cols = [r[1] for r in self._db.execute(
                "PRAGMA table_info(events)").fetchall()]
            if "annotations" not in cols:
                self._db.execute(
                    "ALTER TABLE events ADD COLUMN annotations TEXT")
            self._db.execute(
                "INSERT OR IGNORE INTO meta VALUES ('schema_version', ?)",
                (str(SCHEMA_VERSION),),
            )
            self._db.commit()

    # -- meta / anchors ----------------------------------------------------

    def _get_meta_int(self, key: str, default: int) -> int:
        with self._lock:
            row = self._db.execute(
                "SELECT value FROM meta WHERE key = ?", (key,)
            ).fetchone()
        return int(row[0]) if row is not None else default

    def _set_meta(self, key: str, value: str) -> None:
        # Joins the open transaction when a batch is in flight.
        self._db.execute(
            "INSERT OR REPLACE INTO meta VALUES (?, ?)", (key, value))

    def schema_version(self) -> int:
        return self._get_meta_int("schema_version", 1)

    def _recover(self, legacy: bool) -> None:
        """Load-time torn-tail repair. Rounds (and blocks) above the
        consensus anchor were written by an interrupted, pre-
        transactional consensus pass — a complete pass commits its
        writes and the advanced anchor atomically, so anything beyond
        the anchor is by definition partial and is discarded; the
        events feeding it survive (their sync batches committed) and
        bootstrap's replay recomputes the decisions from scratch."""
        with self._lock:
            if legacy:
                # Database written before the meta table existed: trust
                # its rounds/blocks wholesale (they were written by a
                # graceful-shutdown-only workflow) and seed the anchors
                # from what is present.
                row = self._db.execute(
                    "SELECT COALESCE(MAX(idx), -1) FROM rounds").fetchone()
                self._set_meta("consensus_anchor", str(row[0]))
                row = self._db.execute(
                    "SELECT COALESCE(MAX(rr), -1) FROM blocks").fetchone()
                self._set_meta("last_committed_block", str(row[0]))
                self._db.commit()
                return
            anchor = self._get_meta_int("consensus_anchor", -1)
            cur = self._db.execute(
                "DELETE FROM rounds WHERE idx > ?", (anchor,))
            dropped = cur.rowcount
            dropped += self._db.execute(
                "DELETE FROM blocks WHERE rr > ?", (anchor,)).rowcount
            if dropped:
                self._db.commit()

    def consensus_anchor(self) -> int:
        return self._get_meta_int("consensus_anchor", -1)

    def last_committed_block(self) -> int:
        return self.inmem.last_committed_block()

    def set_last_committed_block(self, rr: int) -> None:
        """Durable delivered-block anchor: advanced by the node AFTER a
        block reached the app, so bootstrap can suppress redelivery of
        everything at or below it (exactly-once across restarts). If a
        batch is open the write rides in it — deferred durability is
        safe because the journal-keeping proxy dedupes redelivery of
        the (small) unmarked window."""
        if rr <= self.inmem.last_committed_block():
            return
        self.inmem.set_last_committed_block(rr)
        with self._lock:
            if self._closed:
                return
            self._set_meta("last_committed_block", str(rr))
            self._commit()

    # -- consensus health (docs/observability.md "Consensus health") ------

    def add_fork_evidence(self, record: dict) -> bool:
        """Equivocation proof, deduped on (creator, idx, forged hash).
        Joins an open batch (the insert that detected the fork runs
        inside a sync batch, whose commit makes the evidence durable
        even though the forged event itself is rejected). Survives
        reset() — evidence is forensic, not consensus state."""
        with self._lock:
            if self._closed:
                return False
            cur = self._db.execute(
                "INSERT OR IGNORE INTO forks VALUES (?, ?, ?, ?)",
                (record["creator"], record["index"], record["forged"],
                 json.dumps(record)),
            )
            fresh = cur.rowcount > 0
            if fresh:
                self._commit()
        return fresh

    def fork_evidence(self) -> List[dict]:
        with self._lock:
            rows = self._db.execute(
                "SELECT data FROM forks ORDER BY creator, idx").fetchall()
        return [json.loads(r[0]) for r in rows]

    def chain_state(self) -> Optional[dict]:
        """Persisted divergence-sentinel chain state (node/health.py),
        or None when never written. Stored next to the delivered-block
        anchor so the two advance atomically: a restarted node resumes
        its chain segment exactly where redelivery resumes blocks."""
        with self._lock:
            row = self._db.execute(
                "SELECT value FROM meta WHERE key = 'chain_state'"
            ).fetchone()
        return json.loads(row[0]) if row is not None else None

    def set_chain_state(self, state: dict) -> None:
        """Meta write WITHOUT a forced commit: the caller pairs this
        with set_last_committed_block (which commits), so the chain
        link and the anchor it corresponds to are durable together."""
        with self._lock:
            if self._closed:
                return
            self._set_meta("chain_state", json.dumps(state))

    # -- batch / transaction protocol --------------------------------------

    def begin_batch(self) -> None:
        """Open (or nest into) an atomic write batch. All writes until
        the matching commit_batch land in one sqlite transaction."""
        with self._lock:
            self._batch_depth += 1

    def commit_batch(self) -> None:
        with self._lock:
            if self._batch_depth == 0:
                return
            self._batch_depth -= 1
            if self._batch_depth == 0:
                self._commit(force=True)

    def rollback_batch(self) -> None:
        """Discard the open batch (all nesting levels): the in-flight
        transaction is rolled back, so a failed sync batch or consensus
        pass leaves no partial writes on disk. The inmem layer is NOT
        rewound — callers abandon it wholesale (restart / engine
        rebuild) after a rollback."""
        with self._lock:
            if self._batch_depth == 0:
                return
            self._batch_depth = 0
            self._rounds_dirty = False
            if not self._closed:
                self._db.rollback()

    def _commit(self, force: bool = False) -> None:
        """Commit the connection's open transaction unless a batch is
        in flight (then the outermost commit_batch commits). A pass
        that wrote rounds advances the consensus anchor inside the same
        transaction — the anchor and the rounds it covers are durable
        or absent together."""
        if self._batch_depth > 0 and not force:
            return
        if self._rounds_dirty:
            self._db.execute(
                "INSERT OR REPLACE INTO meta VALUES ('consensus_anchor', "
                "(SELECT COALESCE(MAX(idx), -1) FROM rounds))")
            self._rounds_dirty = False
        t0 = time.perf_counter_ns()
        self._db.commit()
        dt = time.perf_counter_ns() - t0
        self.fsync_count += 1
        self.fsync_total_ns += dt
        self.fsync_last_ns = dt
        # Registry mirror (docs/observability.md): the batch-commit
        # wall (WAL write + fsync) as a latency distribution, and the
        # commit count, labeled by the fsync policy.
        self._m_fsync.observe(dt / 1e9)
        self._m_fsyncs.inc()

    def wal_bytes(self) -> int:
        try:
            return os.path.getsize(self.path + "-wal")
        except OSError:
            return 0

    def db_bytes(self) -> int:
        """Main database file size (the durable event log + rounds +
        blocks; the WAL is separate — wal_bytes)."""
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def capacity_stats(self) -> dict:
        """Capacity plane (docs/observability.md "Capacity"): the hot
        cache's sizing plus the durable files. The sqlite files are
        the store's true retained footprint; the inmem components are
        the heap working set in front of it."""
        stats = self.inmem.capacity_stats()
        stats["files"] = {
            "db": self.db_bytes(),
            "wal": self.wal_bytes(),
        }
        return stats

    def durability_stats(self) -> Dict[str, object]:
        """Observability payload for /Stats, /debug/phases and bench:
        the durable anchors, the sync policy, and the commit (WAL
        write + fsync) count/latency."""
        with self._lock:
            return {
                "store_sync": self.sync,
                "last_committed_block": self.last_committed_block(),
                "consensus_anchor": self.consensus_anchor(),
                "fsync_count": self.fsync_count,
                "fsync_total_ns": self.fsync_total_ns,
                "fsync_last_ns": self.fsync_last_ns,
                "wal_bytes": self.wal_bytes(),
            }

    # -- participants / roots ---------------------------------------------

    def _db_set_participants(self, participants: Dict[str, int]) -> None:
        with self._lock:
            self._db.executemany(
                "INSERT OR REPLACE INTO participants VALUES (?, ?)",
                list(participants.items()),
            )
            self._db.executemany(
                "INSERT OR REPLACE INTO roots VALUES (?, ?)",
                [
                    (pk, json.dumps(new_base_root().to_dict()))
                    for pk in participants
                ],
            )
            self._commit()

    def _db_participants(self) -> Dict[str, int]:
        with self._lock:
            rows = self._db.execute("SELECT pubkey, id FROM participants").fetchall()
        return {pk: pid for pk, pid in rows}

    # -- Store interface ---------------------------------------------------

    def cache_size(self) -> int:
        return self.inmem.cache_size()

    def participants(self) -> Dict[str, int]:
        return self._participants

    def get_event(self, key: str) -> Event:
        try:
            return self.inmem.get_event(key)
        except StoreError:
            pass
        with self._lock:
            row = self._db.execute(
                "SELECT data, topo, annotations FROM events WHERE hex = ?",
                (key,)
            ).fetchone()
        if row is None:
            raise StoreError(StoreErrType.KEY_NOT_FOUND, key)
        ev = event_from_json_obj(json.loads(row[0]))
        ev.topological_index = row[1]
        return _annotations_from_json(ev, row[2])

    def has_event(self, key: str) -> bool:
        if self.inmem.has_event(key):
            return True
        with self._lock:
            row = self._db.execute(
                "SELECT 1 FROM events WHERE hex = ?", (key,)
            ).fetchone()
        return row is not None

    def set_event(self, event: Event) -> None:
        try:
            self.inmem.set_event(event)
        except StoreError as err:
            if not is_store_err(err, StoreErrType.PASSED_INDEX):
                raise
            # The rolling window aged past this index and the hot LRU
            # no longer holds the hash, so the cache cannot tell an
            # idempotent refresh from a fork — but the db can: an
            # identical hash at (creator, idx) is a refresh and falls
            # through to the upsert below; anything else is a genuine
            # fork.
            with self._lock:
                row = self._db.execute(
                    "SELECT hex FROM events WHERE creator = ? AND idx = ?",
                    (event.creator(), event.index()),
                ).fetchone()
            if row is None or row[0] != event.hex():
                raise
        obj = json.loads(event.marshal())
        with self._lock:
            # Replay order is the autoincrement seq (stable across
            # Reset, which restarts topological_index at 0); the topo
            # column preserves the engine-assigned index for reload.
            # Coordinate back-propagation and round-received marking
            # re-call set_event on old events whose marshaled bytes
            # never change, so conflicts refresh only topo and the
            # runtime annotations (wire/ancestry coordinates, consensus
            # marks) — the db fallback must serve events as usable as
            # the hot cache's.
            self._db.execute(
                "INSERT INTO events (hex, creator, idx, topo, data, "
                "annotations) VALUES (?, ?, ?, ?, ?, ?) "
                "ON CONFLICT(hex) DO UPDATE SET topo = excluded.topo, "
                "annotations = excluded.annotations",
                (
                    event.hex(),
                    event.creator(),
                    event.index(),
                    event.topological_index,
                    json.dumps(obj),
                    _annotations_to_json(event),
                ),
            )
            self._commit()

    def participant_events(self, participant: str, skip: int) -> List[str]:
        try:
            res = self.inmem.participant_events(participant, skip)
            # A freshly loaded store's rolling window is empty and
            # returns [] without error; distinguish "synced empty"
            # (participant known in the window) from "window knows
            # nothing" (is_root) and serve the latter from the db.
            if res:
                return res
            _, is_root = self.inmem.last_from(participant)
            if not is_root:
                return res
        except StoreError:
            pass
        with self._lock:
            rows = self._db.execute(
                "SELECT hex FROM events WHERE creator = ? AND idx > ? "
                "ORDER BY idx",
                (participant, skip),
            ).fetchall()
        return [r[0] for r in rows]

    def participant_window(self, participant: str):
        # Live hot-cache window; coordinates that aged out of it fall
        # back to the per-event probe below, which serves from sqlite.
        return self.inmem.participant_window(participant)

    def participant_event_objects(self, participant: str, skip: int) -> List[Event]:
        try:
            res = self.inmem.participant_event_objects(participant, skip)
            # Same freshly-loaded disambiguation as participant_events:
            # an empty window is only authoritative when the participant
            # has genuinely no events past `skip`.
            if res:
                return res
            _, is_root = self.inmem.last_from(participant)
            if not is_root:
                return res
        except StoreError:
            pass
        with self._lock:
            rows = self._db.execute(
                "SELECT data, topo, annotations FROM events "
                "WHERE creator = ? AND idx > ? ORDER BY idx",
                (participant, skip),
            ).fetchall()
        out = []
        for data, topo, ann in rows:
            ev = event_from_json_obj(json.loads(data))
            ev.topological_index = topo
            out.append(_annotations_from_json(ev, ann))
        return out

    def participant_event(self, participant: str, index: int) -> str:
        try:
            return self.inmem.participant_event(participant, index)
        except StoreError:
            with self._lock:
                row = self._db.execute(
                    "SELECT hex FROM events WHERE creator = ? AND idx = ?",
                    (participant, index),
                ).fetchone()
            if row is None:
                raise StoreError(StoreErrType.KEY_NOT_FOUND, participant)
            return row[0]

    def last_from(self, participant: str) -> Tuple[str, bool]:
        return self.inmem.last_from(participant)

    def known(self) -> Dict[int, int]:
        return self.inmem.known()

    def consensus_events(self) -> List[str]:
        return self.inmem.consensus_events()

    def consensus_events_count(self) -> int:
        return self.inmem.consensus_events_count()

    def add_consensus_event(self, key: str) -> None:
        self.inmem.add_consensus_event(key)

    def get_round(self, r: int) -> RoundInfo:
        try:
            return self.inmem.get_round(r)
        except StoreError:
            pass
        with self._lock:
            row = self._db.execute(
                "SELECT data FROM rounds WHERE idx = ?", (r,)
            ).fetchone()
        if row is None:
            raise StoreError(StoreErrType.KEY_NOT_FOUND, str(r))
        return _round_from_json(row[0])

    def set_round(self, r: int, round_info: RoundInfo) -> None:
        self.inmem.set_round(r, round_info)
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO rounds VALUES (?, ?)",
                (r, _round_to_json(round_info)),
            )
            self._rounds_dirty = True
            self._commit()

    def last_round(self) -> int:
        lr = self.inmem.last_round()
        if lr >= 0:
            return lr
        with self._lock:
            row = self._db.execute("SELECT MAX(idx) FROM rounds").fetchone()
        return row[0] if row and row[0] is not None else -1

    def round_witnesses(self, r: int) -> List[str]:
        try:
            return self.get_round(r).witnesses()
        except StoreError:
            return []

    def round_events(self, r: int) -> int:
        try:
            return len(self.get_round(r).events)
        except StoreError:
            return 0

    def get_root(self, participant: str) -> Root:
        try:
            return self.inmem.get_root(participant)
        except StoreError:
            pass
        with self._lock:
            row = self._db.execute(
                "SELECT data FROM roots WHERE pubkey = ?", (participant,)
            ).fetchone()
        if row is None:
            raise StoreError(StoreErrType.NO_ROOT, participant)
        return Root.from_dict(json.loads(row[0]))

    def get_block(self, rr: int) -> Block:
        try:
            return self.inmem.get_block(rr)
        except StoreError:
            pass
        with self._lock:
            row = self._db.execute(
                "SELECT data FROM blocks WHERE rr = ?", (rr,)
            ).fetchone()
        if row is None:
            raise StoreError(StoreErrType.KEY_NOT_FOUND, str(rr))
        return Block.from_json_obj(json.loads(row[0]))

    def set_block(self, block: Block) -> None:
        self.inmem.set_block(block)
        data = json.dumps(block.to_json_obj())
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO blocks VALUES (?, ?)",
                (block.round_received, data),
            )
            self._commit()

    def reset(self, roots: Dict[str, Root]) -> None:
        """Frame reset: the database drops pre-reset history along with
        the hot cache. Keeping the old event log would poison the next
        restart — bootstrap replays the log against the NEW roots, and
        pre-reset events fail their parent checks there (and the db
        fallback reads would serve stale pre-reset history meanwhile).
        A reset store serves only post-reset state, exactly like
        InmemStore. One transaction: a kill mid-reset leaves the old
        store intact."""
        self.inmem.reset(roots)
        with self._lock:
            self.begin_batch()
            try:
                self._db.execute("DELETE FROM events")
                self._db.execute("DELETE FROM rounds")
                self._db.execute("DELETE FROM blocks")
                self._set_meta("consensus_anchor", "-1")
                self._db.executemany(
                    "INSERT OR REPLACE INTO roots VALUES (?, ?)",
                    [(pk, json.dumps(r.to_dict())) for pk, r in roots.items()],
                )
                self.commit_batch()
            except BaseException:
                self.rollback_batch()
                raise

    def close(self) -> None:
        """Idempotent, exception-safe close: an interrupted batch is
        rolled back (half a protocol batch on disk would violate the
        atomicity contract), otherwise any open transaction is
        committed; double close is a no-op and nothing here raises."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                if self._batch_depth > 0:
                    self._batch_depth = 0
                    self._rounds_dirty = False
                    self._db.rollback()
                else:
                    self._commit()
            except Exception:  # noqa: BLE001 - close must never raise
                try:
                    self._db.rollback()
                except Exception:  # noqa: BLE001
                    pass
            finally:
                try:
                    self._db.close()
                except Exception:  # noqa: BLE001
                    pass

    # -- bootstrap feed ----------------------------------------------------

    def db_topological_events(self) -> Iterator[Event]:
        """Replay the event log in insertion order — reference
        dbTopologicalEvents (badger_store.go:345-386). Consumed by
        Hashgraph.bootstrap()."""
        with self._lock:
            rows = self._db.execute(
                "SELECT data, topo, annotations FROM events ORDER BY seq"
            ).fetchall()
        for data, topo, ann in rows:
            ev = event_from_json_obj(json.loads(data))
            ev.topological_index = topo
            # Wire info rides along so the replay can re-serve diffs;
            # ancestry coordinates are rebuilt by insert_event anyway.
            yield _annotations_from_json(ev, ann)
