"""The 20-method store plugin boundary.

Reference: hashgraph/store.go:3-25. This seam is where alternative
backends slot in: `InmemStore` (volatile, LRU-backed), `FileStore`
(persistent write-through, the BadgerStore analog), and the TPU-side
mirrored store used by the batched engine.

Error convention: methods raise StoreError (common/errors.py) rather
than returning Go-style (value, error) pairs.

Atomicity seam (docs/robustness.md "Crash recovery"): writers group
related mutations — one sync batch's event inserts, one consensus
pass's round/block writes — between `begin_batch()` and
`commit_batch()`. A durable store makes the group one transaction
(all-or-nothing under kill -9); volatile stores treat the calls as
no-ops. `last_committed_block` is the durable delivered-block anchor:
the node advances it after a block reaches the application, and
`Hashgraph.bootstrap` suppresses redelivery at or below it.
"""

from __future__ import annotations

from typing import Dict, List, Protocol, Tuple

from .block import Block
from .event import Event
from .root import Root
from .round_info import RoundInfo


class Store(Protocol):
    def cache_size(self) -> int: ...

    def participants(self) -> Dict[str, int]: ...

    def get_event(self, key: str) -> Event: ...

    def has_event(self, key: str) -> bool: ...

    def set_event(self, event: Event) -> None: ...

    def participant_events(self, participant: str, skip: int) -> List[str]: ...

    def participant_event(self, participant: str, index: int) -> str: ...

    def participant_window(self, participant: str) -> Tuple[List[str], int]:
        """Snapshot of the participant's rolling hash window as
        (items, last_index) — one probe resolves a whole batch of wire
        coordinates positionally (Hashgraph.read_wire_batch)."""
        ...

    def participant_event_objects(self, participant: str, skip: int) -> List[Event]:
        """Events with index > skip, topologically ordered — the O(Δ)
        suffix feed for Core.diff's merge."""
        ...

    def last_from(self, participant: str) -> Tuple[str, bool]:
        """Returns (last event hash or root.X, is_root)."""
        ...

    def known(self) -> Dict[int, int]: ...

    def consensus_events(self) -> List[str]: ...

    def consensus_events_count(self) -> int: ...

    def add_consensus_event(self, key: str) -> None: ...

    def get_round(self, r: int) -> RoundInfo: ...

    def set_round(self, r: int, round_info: RoundInfo) -> None: ...

    def last_round(self) -> int: ...

    def round_witnesses(self, r: int) -> List[str]: ...

    def round_events(self, r: int) -> int: ...

    def get_root(self, participant: str) -> Root: ...

    def get_block(self, rr: int) -> Block: ...

    def set_block(self, block: Block) -> None: ...

    def reset(self, roots: Dict[str, Root]) -> None: ...

    def begin_batch(self) -> None:
        """Open (or nest into) an atomic write batch; writes until the
        matching commit_batch become durable together. No-op for
        volatile stores."""
        ...

    def commit_batch(self) -> None: ...

    def rollback_batch(self) -> None:
        """Discard the open batch's durable writes (crash-equivalent).
        The volatile hot layer is NOT rewound — callers abandon it
        (restart, engine rebuild) after a rollback."""
        ...

    def last_committed_block(self) -> int:
        """Round of the last block known delivered to the application
        (-1 when none) — the exactly-once redelivery anchor."""
        ...

    def set_last_committed_block(self, rr: int) -> None: ...

    def add_fork_evidence(self, record: dict) -> bool:
        """Persist one equivocation evidence record (two signed events
        by one creator at one index — hashgraph/health.py). Deduped on
        (creator, index, forged-hash); returns True when the record is
        new. Durable stores keep evidence across restarts and resets —
        it is forensic state, not consensus state."""
        ...

    def fork_evidence(self) -> List[dict]: ...

    def close(self) -> None: ...
