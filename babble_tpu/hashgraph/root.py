"""Per-participant DAG base.

Reference: hashgraph/root.go:63-76. A Root lets a hashgraph start "from
the middle": each participant's first event must have self-parent X and
other-parent Y matching its Root; `Others` maps event hex -> other-parent
hash for events whose other-parents fall outside a Frame (root.go ex 2).
Base roots are X=Y="", Index=-1, Round=-1.
"""

from __future__ import annotations

from typing import Dict


class Root:
    __slots__ = ("x", "y", "index", "round", "others")

    def __init__(
        self,
        x: str = "",
        y: str = "",
        index: int = -1,
        round: int = -1,
        others: Dict[str, str] | None = None,
    ):
        self.x = x
        self.y = y
        self.index = index
        self.round = round
        self.others = others if others is not None else {}

    def to_dict(self) -> dict:
        return {
            "X": self.x,
            "Y": self.y,
            "Index": self.index,
            "Round": self.round,
            "Others": self.others,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Root":
        return cls(
            x=d["X"],
            y=d["Y"],
            index=d["Index"],
            round=d["Round"],
            others=d.get("Others") or {},
        )

    def __repr__(self) -> str:
        return f"Root(x={self.x[:10]}, y={self.y[:10]}, idx={self.index}, rnd={self.round})"


def new_base_root() -> Root:
    return Root(x="", y="", index=-1, round=-1)
