"""Per-round witness/fame bookkeeping.

Reference: hashgraph/roundInfo.go. Fame is a trilean
(Undefined/True/False); the round pseudo-random number is the XOR of the
famous witnesses' hex hashes interpreted as big ints
(roundInfo.go:100-110).
"""

from __future__ import annotations

import enum
from typing import Dict, List


class Trilean(enum.IntEnum):
    UNDEFINED = 0
    TRUE = 1
    FALSE = 2

    def __str__(self) -> str:
        return ("Undefined", "True", "False")[int(self)]


class RoundEvent:
    __slots__ = ("witness", "famous")

    def __init__(self, witness: bool = False, famous: Trilean = Trilean.UNDEFINED):
        self.witness = witness
        self.famous = famous


class RoundInfo:
    def __init__(self):
        self.events: Dict[str, RoundEvent] = {}
        self.queued = False  # not persisted — reference hashgraph.go:629-637

    def add_event(self, x: str, witness: bool) -> None:
        if x not in self.events:
            self.events[x] = RoundEvent(witness=witness)

    def set_fame(self, x: str, famous: bool) -> None:
        e = self.events.get(x)
        if e is None:
            e = RoundEvent(witness=True)
            self.events[x] = e
        e.famous = Trilean.TRUE if famous else Trilean.FALSE

    def witnesses_decided(self) -> bool:
        return all(
            not e.witness or e.famous != Trilean.UNDEFINED for e in self.events.values()
        )

    def witnesses(self) -> List[str]:
        return [x for x, e in self.events.items() if e.witness]

    def famous_witnesses(self) -> List[str]:
        return [x for x, e in self.events.items() if e.witness and e.famous == Trilean.TRUE]

    def is_decided(self, witness: str) -> bool:
        e = self.events.get(witness)
        return e is not None and e.witness and e.famous != Trilean.UNDEFINED

    def pseudo_random_number(self) -> int:
        res = 0
        for x, e in self.events.items():
            if e.witness and e.famous == Trilean.TRUE:
                res ^= int(x, 16)  # "0x..." parses directly
        return res
