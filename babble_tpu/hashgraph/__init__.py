from .event import Event, EventBody, EventCoordinates, WireBody, WireEvent
from .block import Block
from .root import Root, new_base_root
from .frame import Frame
from .round_info import RoundInfo, RoundEvent, Trilean
from .store import Store
from .inmem_store import InmemStore
from .file_store import FileStore
from .graph import ForkError, Hashgraph, InsertError
from .health import BlockHashChain
from .participant_events import ParticipantEventsCache

__all__ = [
    "Event",
    "EventBody",
    "EventCoordinates",
    "WireBody",
    "WireEvent",
    "Block",
    "Root",
    "new_base_root",
    "Frame",
    "RoundInfo",
    "RoundEvent",
    "Trilean",
    "Store",
    "InmemStore",
    "FileStore",
    "BlockHashChain",
    "ForkError",
    "Hashgraph",
    "InsertError",
    "ParticipantEventsCache",
]
