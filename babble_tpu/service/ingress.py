"""Ingress armor (docs/ingress.md): the admission plane between the
HTTP service and the node's transaction pipeline.

Three cooperating pieces, all owned by one `Ingress` object the node
constructs when `Config.admission` is on:

- **Per-client quotas** — a token bucket per client id (the
  `X-Babble-Client` header, falling back to the remote address), in a
  bounded table with least-recently-seen eviction. A rejected tx is a
  *quota* rejection (the client exceeded its contract), distinct from
  a *shed* (the node is protecting itself).

- **Adaptive load shedding** — a CoDel-style controller over the
  pipeline's measured sojourn time (the oldest entry's age across the
  intake / `work` / `commit_ch` queues, read straight from the PR 15
  instruments). Delay above the target for a full interval starts
  shedding; each subsequent shed comes at `interval / sqrt(count)` —
  the classic square-root ramp — until the delay sinks back under
  target. A hard guard sheds immediately when `work` or `commit_ch`
  sit at >= 90% capacity ("downstream") or the intake queue itself
  overflows ("intake_full"): the whole point is to refuse work at the
  front door *before* the commit path starts dropping.

- **Commit subscriptions** — "tell me when my tx lands": a bounded
  waiter registry keyed by sha256(tx) plus a bounded
  recently-committed ring, resolved from `Node._commit` (and, after a
  restart, from the store's block history), serving both long-poll
  and SSE forms of `GET /subscribe`.

Everything here is accounted: `babble_ingress_admitted_total`,
`babble_ingress_shed_total{reason}`,
`babble_ingress_quota_rejected_total`, and the intake queue's
depth/capacity/wait/drops under the standard `babble_queue_*`
families (queue="intake")."""

from __future__ import annotations

import hashlib
import math
import struct
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..telemetry.queues import InstrumentedQueue, QueueInstrument

# Binary batch-submit frame, following the columnar framing
# conventions (net/columnar.py BBC1/BBD1): magic, little-endian u32
# count, u32 length per tx, then the concatenated raw tx blobs.
TX_BATCH_MAGIC = b"BBB1"

# Shed reasons (the {reason} label on babble_ingress_shed_total).
SHED_OVERLOAD = "overload"        # CoDel: sojourn above target
SHED_DOWNSTREAM = "downstream"    # work/commit_ch near capacity
SHED_INTAKE_FULL = "intake_full"  # intake queue overflow
SHED_SUBSCRIBERS = "subscribers"  # subscriber registry at capacity
SHED_REASONS = (SHED_OVERLOAD, SHED_DOWNSTREAM, SHED_INTAKE_FULL,
                SHED_SUBSCRIBERS)


def tx_digest(tx: bytes) -> str:
    """The subscription key for a transaction: sha256 over the raw
    bytes, hex — what /submit* returns and /subscribe accepts."""
    return hashlib.sha256(tx).hexdigest()


def encode_tx_batch(txs: List[bytes]) -> bytes:
    """Length-prefixed binary batch frame for POST /submit/batch."""
    head = TX_BATCH_MAGIC + struct.pack("<I", len(txs))
    lens = struct.pack(f"<{len(txs)}I", *[len(t) for t in txs])
    return head + lens + b"".join(txs)


def decode_tx_batch(data: bytes, max_tx_bytes: int,
                    max_txs: int = 65536) -> List[bytes]:
    """Decode a TX_BATCH_MAGIC frame; raises ValueError on any
    malformed, oversized, or truncated input (the caller answers
    400/413 — never an exception page)."""
    if len(data) < 8 or data[:4] != TX_BATCH_MAGIC:
        raise ValueError("bad batch magic")
    (count,) = struct.unpack_from("<I", data, 4)
    if count == 0:
        raise ValueError("empty batch")
    if count > max_txs:
        raise ValueError(f"batch of {count} txs exceeds {max_txs}")
    off = 8
    if len(data) < off + 4 * count:
        raise ValueError("truncated batch length table")
    lens = struct.unpack_from(f"<{count}I", data, off)
    off += 4 * count
    txs: List[bytes] = []
    for ln in lens:
        if ln == 0:
            raise ValueError("empty transaction in batch")
        if ln > max_tx_bytes:
            raise ValueError(
                f"transaction of {ln} bytes exceeds {max_tx_bytes}")
        if off + ln > len(data):
            raise ValueError("truncated batch payload")
        txs.append(data[off:off + ln])
        off += ln
    if off != len(data):
        raise ValueError("trailing bytes after batch payload")
    return txs


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill, `burst` cap.
    Not self-locking — the owning table serializes access."""

    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.last = now

    def grant(self, n: int, now: float) -> int:
        """Take up to n tokens; returns how many were granted."""
        if now > self.last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self.last) * self.rate)
            self.last = now
        take = min(n, int(self.tokens))
        self.tokens -= take
        return take

    def retry_after(self) -> float:
        """Seconds until one whole token is available."""
        missing = 1.0 - self.tokens
        if missing <= 0.0 or self.rate <= 0.0:
            return 0.0
        return missing / self.rate


class ClientQuotas:
    """Bounded table of per-client token buckets (least-recently-seen
    eviction keeps a client-id churn attack from growing the table)."""

    def __init__(self, rate: float, burst: float = 0.0,
                 max_clients: int = 4096):
        self.rate = float(rate)
        # burst 0 = auto: a couple of seconds of rate, floor 64, so
        # bursty-but-in-contract clients aren't rejected on arrival
        # phase alone.
        self.burst = float(burst) if burst > 0 else max(2.0 * rate, 64.0)
        self.max_clients = max_clients
        self._lock = threading.Lock()
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self._rejected: Dict[str, int] = {}

    @property
    def enabled(self) -> bool:
        return self.rate > 0.0

    def grant(self, client: str, n: int,
              now: float) -> Tuple[int, float]:
        """Grant up to n submission tokens to `client`; returns
        (granted, retry_after_seconds_for_the_rest)."""
        if not self.enabled:
            return n, 0.0
        with self._lock:
            bucket = self._buckets.get(client)
            if bucket is None:
                while len(self._buckets) >= self.max_clients:
                    self._buckets.popitem(last=False)
                bucket = TokenBucket(self.rate, self.burst, now)
                self._buckets[client] = bucket
            else:
                self._buckets.move_to_end(client)
            granted = bucket.grant(n, now)
            if granted < n:
                self._rejected[client] = (
                    self._rejected.get(client, 0) + (n - granted))
            return granted, bucket.retry_after()

    def table(self, top: int = 16) -> List[Dict[str, object]]:
        """Most-recently-seen clients for /debug/ingress."""
        with self._lock:
            rows = [
                {"client": c, "tokens": round(b.tokens, 1),
                 "rejected": self._rejected.get(c, 0)}
                for c, b in list(self._buckets.items())[-top:]
            ]
        rows.reverse()
        return rows


class AdmissionController:
    """CoDel-style target-delay shedding (docs/ingress.md).

    The signal is the pipeline sojourn time the caller measures (the
    oldest queued item's age) — not queue depth, so capacity changes
    and burst absorption need no retuning. Standing delay above
    `target` for one full `interval` enters the shedding state; while
    shedding, rejections come at interval/sqrt(count) spacing (the
    CoDel ramp), and the first sample back under target exits."""

    def __init__(self, target: float = 0.2, interval: float = 0.5):
        self.target = float(target)
        self.interval = float(interval)
        self._lock = threading.Lock()
        self._first_above = 0.0   # when delay first exceeded target
        self._shedding = False
        self._shed_count = 0      # sheds in the current episode
        self._next_shed = 0.0
        self.episodes = 0         # completed shedding episodes

    def admit(self, delay: float, now: float) -> bool:
        with self._lock:
            if delay < self.target:
                if self._shedding:
                    self.episodes += 1
                self._shedding = False
                self._first_above = 0.0
                return True
            if not self._shedding:
                if self._first_above == 0.0:
                    # First sample above target: arm the interval.
                    self._first_above = now + self.interval
                    return True
                if now < self._first_above:
                    return True
                # Above target for a full interval: start shedding.
                self._shedding = True
                self._shed_count = 1
                self._next_shed = now + self.interval
                return False
            if now >= self._next_shed:
                self._shed_count += 1
                self._next_shed = now + (
                    self.interval / math.sqrt(self._shed_count))
                return False
            return True

    def state(self) -> Dict[str, object]:
        with self._lock:
            return {
                "target_ms": round(self.target * 1000.0, 1),
                "interval_ms": round(self.interval * 1000.0, 1),
                "shedding": self._shedding,
                "episode_sheds": self._shed_count if self._shedding else 0,
                "episodes": self.episodes,
            }


class _Waiter:
    __slots__ = ("event", "result")

    def __init__(self):
        self.event = threading.Event()
        self.result: Optional[Dict[str, object]] = None


class CommitSubscriptions:
    """Bounded digest -> commit-notification registry.

    `resolve` (called from the commit path) records the commit in a
    bounded recently-committed ring and wakes any registered waiters;
    `register`/`wait` is the long-poll/SSE side. The waiter cap bounds
    how many handler threads can park here — beyond it the subscribe
    endpoint sheds (reason "subscribers") instead of accumulating
    blocked threads."""

    def __init__(self, max_waiters: int = 256, recent_cap: int = 4096):
        self.max_waiters = max_waiters
        self.recent_cap = recent_cap
        self._lock = threading.Lock()
        self._waiters: Dict[str, List[_Waiter]] = {}
        self._count = 0
        self._recent: "OrderedDict[str, Dict[str, object]]" = OrderedDict()

    def resolve(self, digest: str, info: Dict[str, object]) -> None:
        with self._lock:
            if digest not in self._recent:
                while len(self._recent) >= self.recent_cap:
                    self._recent.popitem(last=False)
                self._recent[digest] = info
            waiters = self._waiters.pop(digest, None)
            if waiters:
                self._count -= len(waiters)
        for w in waiters or ():
            w.result = info
            w.event.set()

    def lookup(self, digest: str) -> Optional[Dict[str, object]]:
        with self._lock:
            return self._recent.get(digest)

    def register(self, digest: str) -> Optional[_Waiter]:
        """Returns a waiter already resolved (result set), a parked
        waiter to wait on, or None when the registry is full."""
        with self._lock:
            info = self._recent.get(digest)
            if info is not None:
                w = _Waiter()
                w.result = info
                w.event.set()
                return w
            if self._count >= self.max_waiters:
                return None
            w = _Waiter()
            self._waiters.setdefault(digest, []).append(w)
            self._count += 1
            return w

    def unregister(self, digest: str, waiter: _Waiter) -> None:
        with self._lock:
            lst = self._waiters.get(digest)
            if lst and waiter in lst:
                lst.remove(waiter)
                self._count -= 1
                if not lst:
                    del self._waiters[digest]

    def waiter_count(self) -> int:
        with self._lock:
            return self._count


class Ingress:
    """The node's admission plane: quota -> controller -> intake queue,
    plus the commit-subscription registry. Constructed by Node when
    `Config.admission` is on; `--no_admission` leaves it None and the
    service falls back to the bare pre-ingress intake path."""

    # Max txs the intake forwarder coalesces into one work item (one
    # core_lock acquisition, one journal fsync window downstream).
    FORWARD_BATCH = 256

    def __init__(self, node, conf):
        self.node = node
        reg = node.registry
        nl = node._node_label
        cap = int(getattr(conf, "intake_queue", 8192))
        self.intake: InstrumentedQueue = InstrumentedQueue(
            cap, QueueInstrument(reg, "intake", cap, node=nl))
        self.controller = AdmissionController(
            target=float(getattr(conf, "ingress_target_delay", 0.2)),
            interval=float(getattr(conf, "ingress_interval", 0.5)))
        self.quotas = ClientQuotas(
            rate=float(getattr(conf, "quota_rate", 0.0)),
            burst=float(getattr(conf, "quota_burst", 0.0)))
        self.subscriptions = CommitSubscriptions(
            max_waiters=int(getattr(conf, "subscribe_cap", 256)))
        self._m_admitted = reg.counter(
            "babble_ingress_admitted_total",
            "Transactions admitted into the intake queue", node=nl)
        self._m_quota = reg.counter(
            "babble_ingress_quota_rejected_total",
            "Transactions rejected by per-client token-bucket quotas",
            node=nl)
        # Eager children per reason so every family (and the headline
        # reasons) scrape at zero from boot.
        self._m_shed = {
            reason: reg.counter(
                "babble_ingress_shed_total",
                "Transactions shed by the admission controller",
                node=nl, reason=reason)
            for reason in SHED_REASONS
        }

    def capacity_stats(self) -> dict:
        """Capacity plane (docs/observability.md "Capacity"): retained
        bytes of the admission tables — the per-client token-bucket
        map, the parked-subscriber registry, and the recent-commit
        lookup ring. The intake queue itself reports through the
        standard queue families."""
        with self.quotas._lock:
            buckets = len(self.quotas._buckets)
        subs = self.subscriptions
        with subs._lock:
            waiters = subs._count
            recent = len(subs._recent)
        return {
            "components": {
                "ingress_quota_table": {
                    "rows": buckets, "bytes": buckets * 260},
                "ingress_subscriptions": {
                    "rows": waiters + recent,
                    # A parked waiter is an Event + dict entry; a
                    # recent-commit row is a digest -> small-dict map
                    # entry.
                    "bytes": waiters * 400 + recent * 360},
            },
        }

    # -- admission ----------------------------------------------------

    def delay(self) -> float:
        """The controller's signal: the worst sojourn across the
        pipeline's queues (oldest queued item's age)."""
        node = self.node
        return max(self.intake.oldest_age(),
                   node._work.oldest_age(),
                   node.commit_ch.oldest_age())

    def _downstream_saturated(self) -> bool:
        """Hard guard: shed at the front door while the work/commit
        queues still have headroom to drain, never after they drop."""
        node = self.node
        work_cap = node._work.maxsize
        commit_cap = node.commit_ch.maxsize
        return ((work_cap > 0
                 and node._work.qsize() >= 0.9 * work_cap)
                or (commit_cap > 0
                    and node.commit_ch.qsize() >= 0.9 * commit_cap))

    def submit(self, client: str, txs: List[bytes]) -> Dict[str, object]:
        """Run a batch through quota -> controller -> intake. Returns
        per-tx statuses + digests and the aggregate counts the HTTP
        layer turns into a response."""
        now = time.monotonic()
        delay = self.delay()
        saturated = self._downstream_saturated()
        granted, quota_retry = self.quotas.grant(client, len(txs), now)
        statuses: List[str] = []
        digests: List[str] = []
        accepted = shed = 0
        node = self.node
        for i, tx in enumerate(txs):
            digests.append(tx_digest(tx))
            if i >= granted:
                self._m_quota.inc()
                statuses.append("quota_rejected")
                continue
            if saturated:
                self._m_shed[SHED_DOWNSTREAM].inc()
                statuses.append("shed")
                shed += 1
                continue
            if not self.controller.admit(delay, now):
                self._m_shed[SHED_OVERLOAD].inc()
                statuses.append("shed")
                shed += 1
                continue
            node._stamp_tx(tx)
            if self.intake.put_drop(tx):
                self._m_admitted.inc()
                statuses.append("accepted")
                accepted += 1
            else:
                self._m_shed[SHED_INTAKE_FULL].inc()
                statuses.append("shed")
                shed += 1
        quota_rejected = len(txs) - granted
        retry = 0.0
        if shed:
            # Back off proportionally to the measured delay: by the
            # time the client retries, the standing queue should have
            # drained past the target.
            retry = max(1.0, math.ceil(2.0 * max(delay, 0.5)))
        if quota_rejected:
            retry = max(retry, math.ceil(max(quota_retry, 1.0)))
        return {
            "accepted": accepted,
            "shed": shed,
            "quota_rejected": quota_rejected,
            "digests": digests,
            "statuses": statuses,
            "retry_after": int(retry),
        }

    def shed_subscriber(self) -> None:
        self._m_shed[SHED_SUBSCRIBERS].inc()

    # -- commit resolution --------------------------------------------

    def resolve_block(self, block) -> None:
        """Called from Node._commit after app delivery: record every
        committed tx's digest and wake its subscribers."""
        txs = block.transactions or []
        if not txs:
            return
        rr = block.round_received
        for tx in txs:
            self.subscriptions.resolve(
                tx_digest(tx), {"round": rr, "node": self.node.id})

    def wait_commit(self, digest: str,
                    timeout: float) -> Optional[Dict[str, object]]:
        """Long-poll body: resolved info, or None on timeout. Raises
        BlockingIOError when the waiter registry is full (the HTTP
        layer turns that into a 429)."""
        w = self.lookup_or_register(digest)
        if w is None:
            raise BlockingIOError("subscriber registry full")
        if w.event.wait(timeout):
            return w.result
        self.subscriptions.unregister(digest, w)
        return None

    def lookup_or_register(self, digest: str) -> Optional[_Waiter]:
        """Shared by the long-poll and SSE paths: check the recent
        ring, then the store's block history (covers a restarted node
        whose ring is empty — bootstrap replay plus this scan make
        /subscribe restart-proof), then park a waiter."""
        hit = self.subscriptions.lookup(digest)
        if hit is None:
            hit = self._scan_store(digest)
        if hit is not None:
            w = _Waiter()
            w.result = hit
            w.event.set()
            return w
        return self.subscriptions.register(digest)

    def _scan_store(self, digest: str,
                    max_blocks: int = 128) -> Optional[Dict[str, object]]:
        store = self.node.core.hg.store
        try:
            last = int(store.last_committed_block())
        except Exception:  # noqa: BLE001 - store without an anchor
            return None
        for rr in range(last, max(-1, last - max_blocks), -1):
            try:
                block = store.get_block(rr)
            except Exception:  # noqa: BLE001 - pruned/missing round
                continue
            for tx in block.transactions or []:
                if tx_digest(tx) == digest:
                    info = {"round": rr, "node": self.node.id}
                    # Cache in the ring so the next poll is O(1).
                    self.subscriptions.resolve(digest, info)
                    return info
        return None

    # -- observability ------------------------------------------------

    def debug_table(self) -> Dict[str, object]:
        shed = {r: int(c.value) for r, c in self._m_shed.items()}
        return {
            "admitted": int(self._m_admitted.value),
            "shed": shed,
            "quota_rejected": int(self._m_quota.value),
            "controller": self.controller.state(),
            "delay_ms": round(self.delay() * 1000.0, 3),
            "intake": self.intake.instrument.snapshot(),
            "quota": {
                "rate": self.quotas.rate,
                "burst": self.quotas.burst,
                "enabled": self.quotas.enabled,
                "clients": self.quotas.table(),
            },
            "subscribers": self.subscriptions.waiter_count(),
        }
