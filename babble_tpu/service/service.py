"""GET /Stats -> JSON of the node's live counters, with permissive CORS
— reference service/service.go:17-65 — plus GET /debug/profile, the
live-profiling counterpart of the reference's pprof mount
(reference cmd/babble/main.go:12) re-targeted at the device: it
captures a JAX profiler trace of the running node for N seconds.

GET /debug/phases serves the overlap-aware per-phase timers as
structured numbers: for each phase the last/total/calls triple from
Core.phase_ns, plus the engine's pipeline diagnostics (host-blocking
pull share vs the device compute that overlapped gossip ingest) — the
attribution view for "what bounds this node's consensus rate".

GET /metrics serves the process-global telemetry registry in
Prometheus text exposition format (counters, breaker-state gauges,
submit->commit / gossip-RTT / fsync latency histograms), and GET
/debug/trace serves the node's span ring as Chrome trace-event JSON
that loads directly in Perfetto — see docs/observability.md."""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

# /submit has no authentication (localhost-binding is the documented
# guard), so at least bound what one request can make the node buffer.
_MAX_SUBMIT_BYTES = 1 << 20


class Service:
    def __init__(self, bind_addr: str, node):
        host, port_s = bind_addr.rsplit(":", 1)
        self.node = node
        self._profile_lock = threading.Lock()
        self._profile_dir = None
        service = self

        class Handler(BaseHTTPRequestHandler):
            # One serialization + CORS path for every endpoint — the
            # per-endpoint hand-rolled header blocks kept drifting
            # (the /Stats handler sent three CORS headers, the rest
            # one, 404s none and an empty body that scrapers read as
            # "server up, metric gone").
            def _send(self, code, body, content_type):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header(
                    "Access-Control-Allow-Methods",
                    "POST, GET, OPTIONS, PUT, DELETE")
                self.send_header(
                    "Access-Control-Allow-Headers",
                    "Accept, Content-Type, Content-Length, "
                    "Accept-Encoding, X-CSRF-Token, Authorization")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code, obj):
                self._send(code, json.dumps(obj).encode(),
                           "application/json")

            def _not_found(self):
                # A JSON body, not an empty 404: scrapers and probes
                # must fail loudly on a wrong path, not parse "".
                self._json(404, {"error": "unknown path",
                                 "path": urlparse(self.path).path})

            def do_GET(self):  # noqa: N802 - stdlib API
                url = urlparse(self.path)
                if url.path.rstrip("/") in ("/Stats", "/stats", ""):
                    self._json(200, service.node.get_stats())
                elif url.path.rstrip("/") == "/metrics":
                    # Prometheus text exposition (docs/observability
                    # .md): the node's own registry (gossip, consensus,
                    # breaker, latency histograms) merged with the
                    # process-global one (store fsyncs, chaos-transport
                    # faults). Point-in-time gauges (breaker states,
                    # backlog, WAL size) are refreshed here;
                    # counters/histograms are live.
                    from ..telemetry import get_registry, render_merged

                    node = service.node
                    node._refresh_telemetry_gauges()
                    body = render_merged(
                        get_registry(), node.registry).encode()
                    self._send(
                        200, body,
                        "text/plain; version=0.0.4; charset=utf-8")
                elif url.path.rstrip("/") == "/debug/trace":
                    # The span ring as Chrome trace-event JSON — loads
                    # directly in Perfetto (ui.perfetto.dev) for a real
                    # timeline of how syncs, consensus passes, commits
                    # and fast-forwards interleaved.
                    #
                    # ?epoch=cluster rebases the timestamps onto the
                    # shared cluster epoch (telemetry/clock.py), so N
                    # nodes' dumps land on ONE timeline; the raw dump
                    # embeds the clock block instead, and tracemerge
                    # applies it. ?since=<cursor> returns only entries
                    # completed after the cursor (the dump's
                    # babble.next_since), so a long-poll scraper stops
                    # re-downloading the full 4096-span ring per
                    # request.
                    node = service.node
                    q = parse_qs(url.query)
                    try:
                        since = int(q.get("since", ["0"])[0])
                    except ValueError:
                        self._json(400, {"error": "bad since cursor"})
                        return
                    epoch = q.get("epoch", ["mono"])[0]
                    rebase = None
                    meta = {"node": node.id, "epoch": epoch,
                            "clock": node.clock.describe()}
                    if epoch == "cluster":
                        rebase = node.clock.cluster_epoch_ns
                    self._json(200, node.trace.to_chrome_trace(
                        pid=node.id, rebase=rebase, since_seq=since,
                        meta=meta))
                elif url.path.rstrip("/") == "/debug/phases":
                    core = service.node.core
                    phases = {
                        ph: {"last_ns": ent[0], "total_ns": ent[1],
                             "calls": ent[2]}
                        for ph, ent in list(core.phase_ns.items())
                    }
                    out = {"phases": phases}
                    dstats = getattr(core.hg.store, "durability_stats",
                                     None)
                    if dstats is not None:
                        # Durable-path attribution (docs/robustness.md
                        # "Crash recovery"): commit/fsync counters, the
                        # delivered-block and consensus anchors, and
                        # the live WAL size.
                        out["store"] = dstats()
                    engine = getattr(core.hg, "engine", None)
                    if engine is not None:
                        # Host-blocking vs overlapped device time of the
                        # async pipeline (see ops/incremental.py):
                        # c_pull is what the host actually waited at
                        # delta-fetch; overlap is device compute that
                        # ran while the host ingested gossip.
                        out["engine"] = {
                            "backlog": engine.backlog(),
                            "inflight": engine.inflight,
                            "redo_count": engine.redo_count,
                            "last_overlap_ns": engine.last_overlap_ns,
                            "last_pass_phase_ns": dict(engine.phase_ns),
                            "windows": getattr(engine, "_dbg_windows",
                                               None),
                            "c_pull_bytes": getattr(
                                engine, "c_pull_bytes", 0),
                            "cost_report": getattr(
                                engine, "cost_report", None),
                        }
                    self._json(200, out)
                elif url.path.rstrip("/") == "/debug/gossip":
                    # Gossip efficiency observatory (docs/
                    # observability.md "Gossip efficiency"): per-peer
                    # redundancy ratio, new-events-per-sync, bytes per
                    # new event, RTT quantiles, propagation latency,
                    # and the known-map bookkeeping wall — the page
                    # that says how much of the gossip wire actually
                    # buys new events.
                    self._json(200, service.node.get_gossip_stats())
                elif url.path.rstrip("/") == "/debug/peers":
                    # Fault-tolerance view (docs/robustness.md): per-
                    # peer circuit-breaker states plus the engine
                    # degradation counters — the first place to look
                    # when a net is slow or a node stopped committing.
                    # Augmented with the consensus-progress columns
                    # from the gossip health piggyback (each peer's
                    # last known round and how far behind it trails)
                    # and the efficiency columns from the gossip
                    # observatory (redundancy ratio, bytes per new
                    # event) — one endpoint, the whole peer-health
                    # story.
                    node = service.node
                    core = node.core
                    peers = node.get_peer_stats()
                    for addr, prog in node.get_peer_progress().items():
                        peers.setdefault(addr, {}).update(prog)
                    for addr, eff in node.gossip_peer_efficiency() \
                            .items():
                        peers.setdefault(addr, {}).update(eff)
                    # Epidemic broadcast tree membership
                    # (docs/gossip.md): is this peer an eager tree
                    # edge or on the lazy IHAVE plane?
                    for addr, role in node.plumtree_peer_roles() \
                            .items():
                        peers.setdefault(addr, {})["plumtree_edge"] = \
                            role
                    lcr = core.get_last_consensus_round_index()
                    self._json(200, {
                        "engine_state": core.engine_state,
                        "engine_failovers": core.engine_failovers,
                        "last_consensus_round": (
                            -1 if lcr is None else lcr),
                        "round_lag": node.round_lag(),
                        "peers": peers,
                    })
                elif url.path.rstrip("/") == "/debug/consensus":
                    # Consensus health plane (docs/observability.md
                    # "Consensus health"): chain state + divergence
                    # reports (fork point per peer), round/fame
                    # progress, the stall watchdog's live diagnosis,
                    # and the persisted equivocation evidence.
                    self._json(200, service.node.get_consensus_health())
                elif url.path.rstrip("/") == "/debug/hashgraph":
                    # DAG inspector: a bounded window of the event DAG
                    # (parent edges + round/witness/fame/received
                    # annotations) as JSON. Render it to Graphviz DOT
                    # with `python -m babble_tpu.telemetry.dagdump`.
                    q = parse_qs(url.query)
                    try:
                        from_round = q.get("from", [None])[0]
                        from_round = (int(from_round)
                                      if from_round is not None else None)
                        max_rounds = int(q.get("rounds", ["8"])[0])
                        max_events = int(q.get("limit", ["4096"])[0])
                    except ValueError:
                        self._json(400, {"error": "bad query parameter"})
                        return
                    self._json(200, service.node.core.dag_window(
                        from_round=from_round,
                        max_rounds=max(1, max_rounds),
                        max_events=max(1, min(max_events, 65536))))
                elif url.path.rstrip("/") == "/debug/flame":
                    # In-process flame profile (docs/observability.md
                    # "Saturation"): folded-stack text loadable in
                    # speedscope or flamegraph.pl. With the standing
                    # sampler on (--profile_hz > 0) this renders the
                    # last N seconds of its ring instantly; otherwise
                    # it burst-samples inline for N seconds (this
                    # handler thread sleeps, the node is untouched).
                    from ..telemetry import profiler as _profiler

                    try:
                        q = parse_qs(url.query)
                        secs = float(q.get("seconds", ["1"])[0])
                        secs = min(max(secs, 0.1), 30.0)
                    except ValueError:
                        self._json(400, {"error": "bad seconds"})
                        return
                    sampler = _profiler.active()
                    if sampler is not None:
                        text = sampler.folded(secs)
                    else:
                        text = _profiler.burst_folded(secs)
                    self._send(200, text.encode(),
                               "text/plain; charset=utf-8")
                elif url.path.rstrip("/") == "/debug/profile":
                    # Like the reference's pprof mount, this is an
                    # operator tool: bind service_addr to localhost in
                    # production (docs/usage.md). Each capture reuses
                    # ONE per-service directory (previous trace is
                    # replaced), so repeated calls cannot fill /tmp.
                    #
                    # ?cost=1 skips the profiler and returns per-pass
                    # compiled-cost attribution instead: the device
                    # engine AOT-lowers its fused consensus kernel at
                    # the next pass and reports cost_analysis() FLOPs/
                    # bytes (also exported as babble_engine_pass_flops/
                    # _bytes gauges). 202 while the capture is pending
                    # on an idle node — poll again.
                    try:
                        q = parse_qs(url.query)
                        secs = float(q.get("seconds", ["5"])[0])
                        secs = min(max(secs, 0.1), 30.0)
                    except ValueError:
                        self._json(400, {"error": "bad seconds"})
                        return
                    if q.get("cost", ["0"])[0] not in ("0", ""):
                        report = service.node.core.engine_cost_report(
                            wait_s=secs)
                        if report is None:
                            self._json(400, {
                                "error": "cost attribution needs the "
                                         "device engine (--engine tpu)"})
                        elif not report:
                            self._json(202, {"pending": True})
                        else:
                            self._json(200, {"cost": report})
                        return
                    if not service._profile_lock.acquire(blocking=False):
                        self._json(409, {"error": "profile in progress"})
                        return
                    try:
                        import shutil

                        import jax

                        if service._profile_dir is None:
                            service._profile_dir = tempfile.mkdtemp(
                                prefix="babble-profile-")
                        else:
                            shutil.rmtree(service._profile_dir,
                                          ignore_errors=True)
                            os.makedirs(service._profile_dir,
                                        exist_ok=True)
                        jax.profiler.start_trace(service._profile_dir)
                        time.sleep(secs)
                        jax.profiler.stop_trace()
                        self._json(200, {"trace_dir": service._profile_dir,
                                         "seconds": secs})
                    except Exception as exc:  # noqa: BLE001
                        self._json(500, {"error": str(exc)})
                    finally:
                        service._profile_lock.release()
                else:
                    self._not_found()

            def do_POST(self):  # noqa: N802 - stdlib API
                url = urlparse(self.path)
                if url.path.rstrip("/") == "/submit":
                    # Transaction intake without a socket app client:
                    # the body is one raw transaction. Used by the
                    # crash harness (whose nodes run --journal) and
                    # handy for curl-driven demos; like /debug/*, bind
                    # service_addr to localhost in production.
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                        if length <= 0:
                            self._json(400, {"error": "empty transaction"})
                            return
                        if length > _MAX_SUBMIT_BYTES:
                            # Drain and discard in bounded chunks:
                            # responding with the body unread breaks
                            # the client's pipe mid-send, and memory
                            # must stay capped either way.
                            remaining = length
                            while remaining > 0:
                                chunk = self.rfile.read(
                                    min(remaining, 65536))
                                if not chunk:
                                    break
                                remaining -= len(chunk)
                            self._json(413, {"error": "transaction too "
                                             f"large (max {_MAX_SUBMIT_BYTES}"
                                             " bytes)"})
                            return
                        tx = self.rfile.read(length)
                        if not tx:
                            self._json(400, {"error": "empty transaction"})
                            return
                        service.node.submit_tx(tx)
                        self._json(200, {"submitted": len(tx)})
                    except Exception as exc:  # noqa: BLE001
                        self._json(500, {"error": str(exc)})
                else:
                    self._not_found()

            def do_OPTIONS(self):  # noqa: N802 - CORS preflight
                self.send_response(200)
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header(
                    "Access-Control-Allow-Methods", "POST, GET, OPTIONS, PUT, DELETE"
                )
                self.end_headers()

            def log_message(self, fmt, *args):  # silence per-request noise
                pass

        self._server = ThreadingHTTPServer((host, int(port_s)), Handler)
        self.addr = f"{host}:{self._server.server_address[1]}"
        self._thread: threading.Thread | None = None

    def serve(self) -> None:
        """Blocking serve — reference Service.Serve."""
        self._server.serve_forever(poll_interval=0.1)

    def serve_async(self) -> None:
        self._thread = threading.Thread(target=self.serve, daemon=True,
                                        name="babble-service")
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
