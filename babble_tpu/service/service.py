"""GET /Stats -> JSON of the node's live counters, with permissive CORS
— reference service/service.go:17-65 — plus GET /debug/profile, the
live-profiling counterpart of the reference's pprof mount
(reference cmd/babble/main.go:12) re-targeted at the device: it
captures a JAX profiler trace of the running node for N seconds.

GET /debug/phases serves the overlap-aware per-phase timers as
structured numbers: for each phase the last/total/calls triple from
Core.phase_ns, plus the engine's pipeline diagnostics (host-blocking
pull share vs the device compute that overlapped gossip ingest) — the
attribution view for "what bounds this node's consensus rate".

GET /metrics serves the process-global telemetry registry in
Prometheus text exposition format (counters, breaker-state gauges,
submit->commit / gossip-RTT / fsync latency histograms), and GET
/debug/trace serves the node's span ring as Chrome trace-event JSON
that loads directly in Perfetto — see docs/observability.md."""

from __future__ import annotations

import base64
import hmac
import json
import os
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .ingress import TX_BATCH_MAGIC, decode_tx_batch

# /submit defaults to no authentication (localhost-binding is the
# documented guard; --submit_token adds a bearer token), so at least
# bound what one request can make the node buffer. The cap is enforced
# while READING, not just against Content-Length — a chunked or
# lying-length client cannot make the handler buffer past it.
_MAX_SUBMIT_BYTES = 1 << 20
# A /submit/batch body may carry many transactions; each tx stays
# under _MAX_SUBMIT_BYTES, the frame under this.
_MAX_BATCH_BYTES = 8 << 20


class Service:
    def __init__(self, bind_addr: str, node):
        host, port_s = bind_addr.rsplit(":", 1)
        self.node = node
        self._profile_lock = threading.Lock()
        self._profile_dir = None
        service = self

        class Handler(BaseHTTPRequestHandler):
            # One serialization + CORS path for every endpoint — the
            # per-endpoint hand-rolled header blocks kept drifting
            # (the /Stats handler sent three CORS headers, the rest
            # one, 404s none and an empty body that scrapers read as
            # "server up, metric gone").
            def _send(self, code, body, content_type, extra=None):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header(
                    "Access-Control-Allow-Methods",
                    "POST, GET, OPTIONS, PUT, DELETE")
                self.send_header(
                    "Access-Control-Allow-Headers",
                    "Accept, Content-Type, Content-Length, "
                    "Accept-Encoding, X-CSRF-Token, Authorization, "
                    "X-Babble-Client")
                for k, v in (extra or {}).items():
                    self.send_header(k, str(v))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code, obj, extra=None):
                self._send(code, json.dumps(obj).encode(),
                           "application/json", extra=extra)

            def _not_found(self):
                # A JSON body, not an empty 404: scrapers and probes
                # must fail loudly on a wrong path, not parse "".
                self._json(404, {"error": "unknown path",
                                 "path": urlparse(self.path).path})

            def do_GET(self):  # noqa: N802 - stdlib API
                url = urlparse(self.path)
                if url.path.rstrip("/") in ("/Stats", "/stats", ""):
                    self._json(200, service.node.get_stats())
                elif url.path.rstrip("/") == "/metrics":
                    # Prometheus text exposition (docs/observability
                    # .md): the node's own registry (gossip, consensus,
                    # breaker, latency histograms) merged with the
                    # process-global one (store fsyncs, chaos-transport
                    # faults). Point-in-time gauges (breaker states,
                    # backlog, WAL size) are refreshed here;
                    # counters/histograms are live.
                    from ..telemetry import get_registry, render_merged

                    node = service.node
                    node._refresh_telemetry_gauges()
                    body = render_merged(
                        get_registry(), node.registry).encode()
                    self._send(
                        200, body,
                        "text/plain; version=0.0.4; charset=utf-8")
                elif url.path.rstrip("/") == "/debug/trace":
                    # The span ring as Chrome trace-event JSON — loads
                    # directly in Perfetto (ui.perfetto.dev) for a real
                    # timeline of how syncs, consensus passes, commits
                    # and fast-forwards interleaved.
                    #
                    # ?epoch=cluster rebases the timestamps onto the
                    # shared cluster epoch (telemetry/clock.py), so N
                    # nodes' dumps land on ONE timeline; the raw dump
                    # embeds the clock block instead, and tracemerge
                    # applies it. ?since=<cursor> returns only entries
                    # completed after the cursor (the dump's
                    # babble.next_since), so a long-poll scraper stops
                    # re-downloading the full 4096-span ring per
                    # request.
                    node = service.node
                    q = parse_qs(url.query)
                    try:
                        since = int(q.get("since", ["0"])[0])
                    except ValueError:
                        self._json(400, {"error": "bad since cursor"})
                        return
                    epoch = q.get("epoch", ["mono"])[0]
                    rebase = None
                    meta = {"node": node.id, "epoch": epoch,
                            "clock": node.clock.describe()}
                    if epoch == "cluster":
                        rebase = node.clock.cluster_epoch_ns
                    self._json(200, node.trace.to_chrome_trace(
                        pid=node.id, rebase=rebase, since_seq=since,
                        meta=meta))
                elif url.path.rstrip("/") == "/debug/phases":
                    core = service.node.core
                    phases = {
                        ph: {"last_ns": ent[0], "total_ns": ent[1],
                             "calls": ent[2]}
                        for ph, ent in list(core.phase_ns.items())
                    }
                    out = {"phases": phases}
                    dstats = getattr(core.hg.store, "durability_stats",
                                     None)
                    if dstats is not None:
                        # Durable-path attribution (docs/robustness.md
                        # "Crash recovery"): commit/fsync counters, the
                        # delivered-block and consensus anchors, and
                        # the live WAL size.
                        out["store"] = dstats()
                    engine = getattr(core.hg, "engine", None)
                    if engine is not None:
                        # Host-blocking vs overlapped device time of the
                        # async pipeline (see ops/incremental.py):
                        # c_pull is what the host actually waited at
                        # delta-fetch; overlap is device compute that
                        # ran while the host ingested gossip.
                        out["engine"] = {
                            "backlog": engine.backlog(),
                            "inflight": engine.inflight,
                            "redo_count": engine.redo_count,
                            "last_overlap_ns": engine.last_overlap_ns,
                            "last_pass_phase_ns": dict(engine.phase_ns),
                            "windows": getattr(engine, "_dbg_windows",
                                               None),
                            "c_pull_bytes": getattr(
                                engine, "c_pull_bytes", 0),
                            "cost_report": getattr(
                                engine, "cost_report", None),
                        }
                    self._json(200, out)
                elif url.path.rstrip("/") == "/debug/gossip":
                    # Gossip efficiency observatory (docs/
                    # observability.md "Gossip efficiency"): per-peer
                    # redundancy ratio, new-events-per-sync, bytes per
                    # new event, RTT quantiles, propagation latency,
                    # and the known-map bookkeeping wall — the page
                    # that says how much of the gossip wire actually
                    # buys new events.
                    self._json(200, service.node.get_gossip_stats())
                elif url.path.rstrip("/") == "/debug/peers":
                    # Fault-tolerance view (docs/robustness.md): per-
                    # peer circuit-breaker states plus the engine
                    # degradation counters — the first place to look
                    # when a net is slow or a node stopped committing.
                    # Augmented with the consensus-progress columns
                    # from the gossip health piggyback (each peer's
                    # last known round and how far behind it trails)
                    # and the efficiency columns from the gossip
                    # observatory (redundancy ratio, bytes per new
                    # event) — one endpoint, the whole peer-health
                    # story.
                    node = service.node
                    core = node.core
                    peers = node.get_peer_stats()
                    for addr, prog in node.get_peer_progress().items():
                        peers.setdefault(addr, {}).update(prog)
                    for addr, eff in node.gossip_peer_efficiency() \
                            .items():
                        peers.setdefault(addr, {}).update(eff)
                    # Epidemic broadcast tree membership
                    # (docs/gossip.md): is this peer an eager tree
                    # edge or on the lazy IHAVE plane?
                    for addr, role in node.plumtree_peer_roles() \
                            .items():
                        peers.setdefault(addr, {})["plumtree_edge"] = \
                            role
                    lcr = core.get_last_consensus_round_index()
                    self._json(200, {
                        "engine_state": core.engine_state,
                        "engine_failovers": core.engine_failovers,
                        "last_consensus_round": (
                            -1 if lcr is None else lcr),
                        "round_lag": node.round_lag(),
                        "peers": peers,
                    })
                elif url.path.rstrip("/") == "/debug/capacity":
                    # Capacity observatory (docs/observability.md
                    # "Capacity"): per-subsystem retained bytes,
                    # durable file sizes, cache efficiency, process
                    # RSS/GC, device HBM carries, and the windowed
                    # growth slopes with the ranked top-growers table
                    # and time-to-budget projection. {"enabled":
                    # false} under --no_capacity.
                    self._json(200, service.node.get_capacity_stats())
                elif url.path.rstrip("/") == "/debug/consensus":
                    # Consensus health plane (docs/observability.md
                    # "Consensus health"): chain state + divergence
                    # reports (fork point per peer), round/fame
                    # progress, the stall watchdog's live diagnosis,
                    # and the persisted equivocation evidence.
                    self._json(200, service.node.get_consensus_health())
                elif url.path.rstrip("/") == "/debug/hashgraph":
                    # DAG inspector: a bounded window of the event DAG
                    # (parent edges + round/witness/fame/received
                    # annotations) as JSON. Render it to Graphviz DOT
                    # with `python -m babble_tpu.telemetry.dagdump`.
                    q = parse_qs(url.query)
                    try:
                        from_round = q.get("from", [None])[0]
                        from_round = (int(from_round)
                                      if from_round is not None else None)
                        max_rounds = int(q.get("rounds", ["8"])[0])
                        max_events = int(q.get("limit", ["4096"])[0])
                    except ValueError:
                        self._json(400, {"error": "bad query parameter"})
                        return
                    self._json(200, service.node.core.dag_window(
                        from_round=from_round,
                        max_rounds=max(1, max_rounds),
                        max_events=max(1, min(max_events, 65536))))
                elif url.path.rstrip("/") == "/debug/flame":
                    # In-process flame profile (docs/observability.md
                    # "Saturation"): folded-stack text loadable in
                    # speedscope or flamegraph.pl. With the standing
                    # sampler on (--profile_hz > 0) this renders the
                    # last N seconds of its ring instantly; otherwise
                    # it burst-samples inline for N seconds (this
                    # handler thread sleeps, the node is untouched).
                    from ..telemetry import profiler as _profiler

                    try:
                        q = parse_qs(url.query)
                        secs = float(q.get("seconds", ["1"])[0])
                        secs = min(max(secs, 0.1), 30.0)
                    except ValueError:
                        self._json(400, {"error": "bad seconds"})
                        return
                    sampler = _profiler.active()
                    if sampler is not None:
                        text = sampler.folded(secs)
                    else:
                        text = _profiler.burst_folded(secs)
                    self._send(200, text.encode(),
                               "text/plain; charset=utf-8")
                elif url.path.rstrip("/") == "/debug/profile":
                    # Like the reference's pprof mount, this is an
                    # operator tool: bind service_addr to localhost in
                    # production (docs/usage.md). Each capture reuses
                    # ONE per-service directory (previous trace is
                    # replaced), so repeated calls cannot fill /tmp.
                    #
                    # ?cost=1 skips the profiler and returns per-pass
                    # compiled-cost attribution instead: the device
                    # engine AOT-lowers its fused consensus kernel at
                    # the next pass and reports cost_analysis() FLOPs/
                    # bytes (also exported as babble_engine_pass_flops/
                    # _bytes gauges). 202 while the capture is pending
                    # on an idle node — poll again.
                    try:
                        q = parse_qs(url.query)
                        secs = float(q.get("seconds", ["5"])[0])
                        secs = min(max(secs, 0.1), 30.0)
                    except ValueError:
                        self._json(400, {"error": "bad seconds"})
                        return
                    if q.get("cost", ["0"])[0] not in ("0", ""):
                        report = service.node.core.engine_cost_report(
                            wait_s=secs)
                        if report is None:
                            self._json(400, {
                                "error": "cost attribution needs the "
                                         "device engine (--engine tpu)"})
                        elif not report:
                            self._json(202, {"pending": True})
                        else:
                            self._json(200, {"cost": report})
                        return
                    if not service._profile_lock.acquire(blocking=False):
                        self._json(409, {"error": "profile in progress"})
                        return
                    try:
                        import shutil

                        import jax

                        if service._profile_dir is None:
                            service._profile_dir = tempfile.mkdtemp(
                                prefix="babble-profile-")
                        else:
                            shutil.rmtree(service._profile_dir,
                                          ignore_errors=True)
                            os.makedirs(service._profile_dir,
                                        exist_ok=True)
                        jax.profiler.start_trace(service._profile_dir)
                        time.sleep(secs)
                        jax.profiler.stop_trace()
                        self._json(200, {"trace_dir": service._profile_dir,
                                         "seconds": secs})
                    except Exception as exc:  # noqa: BLE001
                        self._json(500, {"error": str(exc)})
                    finally:
                        service._profile_lock.release()
                elif url.path.rstrip("/") == "/subscribe":
                    self._handle_subscribe(url)
                elif url.path.rstrip("/") == "/debug/ingress":
                    # Admission-plane table (docs/ingress.md):
                    # admitted/shed/quota counters, the CoDel
                    # controller's live state and delay estimate, the
                    # intake queue snapshot, and the most-recently-
                    # seen clients' token buckets.
                    ingress = getattr(service.node, "ingress", None)
                    if ingress is None:
                        self._json(200, {"admission": False})
                    else:
                        out = {"admission": True}
                        out.update(ingress.debug_table())
                        self._json(200, out)
                else:
                    self._not_found()

            def _handle_subscribe(self, url):
                # Commit-subscription stream (docs/ingress.md):
                # ?tx=<sha256 hex of the raw tx bytes — the digest
                # /submit* returns>. Long-poll by default (200 with
                # the commit record, 204 on timeout); SSE with
                # Accept: text/event-stream or ?sse=1 (heartbeat
                # comments while waiting, one `commit` event, close).
                ingress = getattr(service.node, "ingress", None)
                if ingress is None:
                    self._json(503, {"error": "admission plane disabled "
                                     "(--no_admission)"})
                    return
                q = parse_qs(url.query)
                digest = q.get("tx", [""])[0].strip().lower()
                if len(digest) != 64 or any(
                        c not in "0123456789abcdef" for c in digest):
                    self._json(400, {"error": "tx must be the 64-char "
                                     "sha256 hex digest of the raw "
                                     "transaction bytes"})
                    return
                try:
                    timeout = float(q.get("timeout", ["30"])[0])
                except ValueError:
                    self._json(400, {"error": "bad timeout"})
                    return
                timeout = min(max(timeout, 0.0), 120.0)
                sse = (q.get("sse", ["0"])[0] not in ("0", "")
                       or "text/event-stream"
                       in (self.headers.get("Accept") or ""))
                try:
                    waiter = ingress.lookup_or_register(digest)
                except Exception as exc:  # noqa: BLE001
                    self._json(500, {"error": str(exc)})
                    return
                if waiter is None:
                    # Registry full: shed, never park an unbounded
                    # number of handler threads.
                    ingress.shed_subscriber()
                    self._json(429, {"error": "subscriber registry "
                                     "full", "retry_after": 1},
                               extra={"Retry-After": 1})
                    return
                if not sse:
                    try:
                        if waiter.event.wait(timeout):
                            self._json(200, dict(waiter.result,
                                                 tx=digest))
                        else:
                            self._send(204, b"", "application/json")
                    finally:
                        ingress.subscriptions.unregister(digest, waiter)
                    return
                # SSE: headers first, heartbeat comments while
                # waiting, one `commit` (or `timeout`) event, close.
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Access-Control-Allow-Origin", "*")
                self.end_headers()
                deadline = time.monotonic() + timeout
                try:
                    while True:
                        left = deadline - time.monotonic()
                        if waiter.event.wait(min(max(left, 0.0), 5.0)):
                            payload = json.dumps(
                                dict(waiter.result, tx=digest))
                            self.wfile.write(
                                f"event: commit\ndata: {payload}\n\n"
                                .encode())
                            self.wfile.flush()
                            return
                        if left <= 0:
                            self.wfile.write(
                                b"event: timeout\ndata: {}\n\n")
                            self.wfile.flush()
                            return
                        self.wfile.write(b": ping\n\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-stream
                finally:
                    self.close_connection = True
                    ingress.subscriptions.unregister(digest, waiter)

            # -- intake plumbing (docs/ingress.md) -------------------

            def _client_id(self):
                # Per-client quota key: explicit client id header,
                # falling back to the remote address.
                cid = (self.headers.get("X-Babble-Client") or "").strip()
                return cid or self.client_address[0]

            def _auth_ok(self, cap):
                """Bearer-token gate for /submit* (Config.submit_token;
                constant-time compare). Drains the body (bounded)
                before a 401 so the client never dies on a broken
                pipe mid-send."""
                token = getattr(service.node.conf, "submit_token", "")
                if not token:
                    return True
                header = (self.headers.get("Authorization") or "").strip()
                if hmac.compare_digest(header, "Bearer " + token):
                    return True
                self._drain_body(cap)
                self._json(401, {"error": "unauthorized"},
                           extra={"WWW-Authenticate": "Bearer"})
                return False

            def _drain_body(self, cap):
                """Discard up to ~cap bytes of request body in bounded
                chunks (the PR 4-review EPIPE lesson: responding with
                the body unread breaks the client's pipe mid-send;
                memory must stay capped either way). Past the bound
                the connection is closed instead."""
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    length = 0
                remaining = min(length, cap)
                while remaining > 0:
                    chunk = self.rfile.read(min(remaining, 65536))
                    if not chunk:
                        break
                    remaining -= len(chunk)
                if length > cap:
                    self.close_connection = True

            def _read_body(self, cap, what="transaction"):
                """Read the request body with the cap enforced WHILE
                reading — Content-Length is a claim, not a contract:
                chunked bodies are decoded with a running cap, and a
                plain body is read in bounded chunks up to min(length,
                cap). Returns the bytes, or None after answering the
                error itself."""
                te = (self.headers.get("Transfer-Encoding") or "").lower()
                if "chunked" in te:
                    return self._read_chunked(cap, what)
                cl = self.headers.get("Content-Length")
                if cl is None:
                    self._json(411, {"error": "length required"})
                    return None
                try:
                    length = int(cl)
                except ValueError:
                    self._json(400, {"error": "bad Content-Length"})
                    return None
                if length < 0:
                    self._json(400, {"error": "bad Content-Length"})
                    return None
                if length > cap:
                    self._drain_body(cap)
                    self._json(413, {"error": f"{what} too large "
                                     f"(max {cap} bytes)"})
                    return None
                chunks = []
                remaining = length
                while remaining > 0:
                    chunk = self.rfile.read(min(remaining, 65536))
                    if not chunk:
                        break
                    chunks.append(chunk)
                    remaining -= len(chunk)
                return b"".join(chunks)

            def _read_chunked(self, cap, what):
                """Decode a chunked body with a running size cap: a
                client whose chunks sum past the cap gets the 413 at
                the moment of overflow and the connection closed (the
                remainder cannot be skipped without unbounded reads)."""
                total = []
                size_sum = 0
                while True:
                    line = self.rfile.readline(34)
                    if not line:
                        self._json(400, {"error": "truncated chunked body"})
                        self.close_connection = True
                        return None
                    try:
                        size = int(line.strip().split(b";")[0], 16)
                    except ValueError:
                        self._json(400, {"error": "bad chunk header"})
                        self.close_connection = True
                        return None
                    if size == 0:
                        # Consume the trailer section up to the blank
                        # line terminating the body.
                        while True:
                            t = self.rfile.readline(1024)
                            if not t or t in (b"\r\n", b"\n"):
                                break
                        break
                    size_sum += size
                    if size_sum > cap:
                        self.close_connection = True
                        self._json(413, {"error": f"{what} too large "
                                         f"(max {cap} bytes)"})
                        return None
                    remaining = size
                    while remaining > 0:
                        chunk = self.rfile.read(min(remaining, 65536))
                        if not chunk:
                            self._json(400, {"error":
                                             "truncated chunked body"})
                            self.close_connection = True
                            return None
                        total.append(chunk)
                        remaining -= len(chunk)
                    self.rfile.readline(8)  # trailing CRLF
                return b"".join(total)

            def _shed_response(self, res):
                """429 for a fully-rejected request: Retry-After from
                the controller's delay estimate (shed) or the token
                bucket's refill time (quota)."""
                reason = ("quota" if res["quota_rejected"]
                          and not res["shed"] else "overload")
                self._json(429, {
                    "error": "rejected by admission control",
                    "reason": reason,
                    "shed": res["shed"],
                    "quota_rejected": res["quota_rejected"],
                    "retry_after": res["retry_after"],
                }, extra={"Retry-After": res["retry_after"]})

            def _handle_submit(self):
                # Transaction intake without a socket app client: the
                # body is one raw transaction. Used by the crash
                # harness (whose nodes run --journal) and handy for
                # curl-driven demos; like /debug/*, bind service_addr
                # to localhost in production.
                try:
                    if not self._auth_ok(_MAX_SUBMIT_BYTES):
                        return
                    tx = self._read_body(_MAX_SUBMIT_BYTES)
                    if tx is None:
                        return
                    if not tx:
                        self._json(400, {"error": "empty transaction"})
                        return
                    ingress = getattr(service.node, "ingress", None)
                    if ingress is None:
                        # --no_admission: today's bare intake path,
                        # byte-for-byte.
                        service.node.submit_tx(tx)
                        self._json(200, {"submitted": len(tx)})
                        return
                    res = ingress.submit(self._client_id(), [tx])
                    if res["accepted"]:
                        self._json(200, {"submitted": len(tx),
                                         "digest": res["digests"][0]})
                    else:
                        self._shed_response(res)
                except Exception as exc:  # noqa: BLE001
                    self._json(500, {"error": str(exc)})

            def _handle_submit_batch(self):
                # Batched intake: a length-prefixed binary frame
                # (ingress.encode_tx_batch, magic BBB1 following the
                # columnar framing conventions) or a JSON array of
                # base64 transactions. Per-tx statuses come back
                # aligned with the request order.
                try:
                    if not self._auth_ok(_MAX_BATCH_BYTES):
                        return
                    body = self._read_body(_MAX_BATCH_BYTES, what="batch")
                    if body is None:
                        return
                    if not body:
                        self._json(400, {"error": "empty batch"})
                        return
                    try:
                        if body[:4] == TX_BATCH_MAGIC:
                            txs = decode_tx_batch(body, _MAX_SUBMIT_BYTES)
                        else:
                            doc = json.loads(body)
                            if isinstance(doc, dict):
                                doc = doc.get("txs")
                            if not isinstance(doc, list) or not doc:
                                raise ValueError(
                                    "body must be a JSON array of "
                                    "base64 transactions or a BBB1 "
                                    "binary frame")
                            txs = [base64.b64decode(t) for t in doc]
                            for tx in txs:
                                if not tx:
                                    raise ValueError(
                                        "empty transaction in batch")
                                if len(tx) > _MAX_SUBMIT_BYTES:
                                    raise ValueError(
                                        "transaction exceeds "
                                        f"{_MAX_SUBMIT_BYTES} bytes")
                    except Exception as exc:  # noqa: BLE001
                        self._json(400, {"error": f"bad batch: {exc}"})
                        return
                    res = service.node.submit_batch(
                        txs, client=self._client_id())
                    if res["accepted"] == 0 and len(txs) > 0 \
                            and getattr(service.node, "ingress", None) \
                            is not None:
                        self._shed_response(res)
                        return
                    extra = ({"Retry-After": res["retry_after"]}
                             if res["retry_after"] else None)
                    self._json(200, {
                        "submitted": res["accepted"],
                        "shed": res["shed"],
                        "quota_rejected": res["quota_rejected"],
                        "digests": res["digests"],
                        "statuses": res["statuses"],
                        "retry_after": res["retry_after"],
                    }, extra=extra)
                except Exception as exc:  # noqa: BLE001
                    self._json(500, {"error": str(exc)})

            def do_POST(self):  # noqa: N802 - stdlib API
                path = urlparse(self.path).path.rstrip("/")
                if path == "/submit":
                    self._handle_submit()
                elif path == "/submit/batch":
                    self._handle_submit_batch()
                else:
                    self._not_found()

            def do_OPTIONS(self):  # noqa: N802 - CORS preflight
                self.send_response(200)
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header(
                    "Access-Control-Allow-Methods", "POST, GET, OPTIONS, PUT, DELETE"
                )
                self.end_headers()

            def log_message(self, fmt, *args):  # silence per-request noise
                pass

        self._server = ThreadingHTTPServer((host, int(port_s)), Handler)
        self.addr = f"{host}:{self._server.server_address[1]}"
        self._thread: threading.Thread | None = None

    def serve(self) -> None:
        """Blocking serve — reference Service.Serve."""
        self._server.serve_forever(poll_interval=0.1)

    def serve_async(self) -> None:
        self._thread = threading.Thread(target=self.serve, daemon=True,
                                        name="babble-service")
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
