"""GET /Stats -> JSON of the node's live counters, with permissive CORS
— reference service/service.go:17-65."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Service:
    def __init__(self, bind_addr: str, node):
        host, port_s = bind_addr.rsplit(":", 1)
        self.node = node
        service = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API
                if self.path.rstrip("/") in ("/Stats", "/stats", ""):
                    body = json.dumps(service.node.get_stats()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Access-Control-Allow-Origin", "*")
                    self.send_header(
                        "Access-Control-Allow-Methods", "POST, GET, OPTIONS, PUT, DELETE"
                    )
                    self.send_header(
                        "Access-Control-Allow-Headers",
                        "Accept, Content-Type, Content-Length, Accept-Encoding, "
                        "X-CSRF-Token, Authorization",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

            def do_OPTIONS(self):  # noqa: N802 - CORS preflight
                self.send_response(200)
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header(
                    "Access-Control-Allow-Methods", "POST, GET, OPTIONS, PUT, DELETE"
                )
                self.end_headers()

            def log_message(self, fmt, *args):  # silence per-request noise
                pass

        self._server = ThreadingHTTPServer((host, int(port_s)), Handler)
        self.addr = f"{host}:{self._server.server_address[1]}"
        self._thread: threading.Thread | None = None

    def serve(self) -> None:
        """Blocking serve — reference Service.Serve."""
        self._server.serve_forever(poll_interval=0.1)

    def serve_async(self) -> None:
        self._thread = threading.Thread(target=self.serve, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
