"""HTTP observability service — reference service/service.go."""

from .service import Service

__all__ = ["Service"]
