#!/usr/bin/env bash
# Poll every node's /Stats once per second — reference
# docker/watcher/watch.sh:1-12.
set -u
NODES="${NODES:-4}"
while true; do
  for i in $(seq 1 "$NODES"); do
    echo "--- node$i ---"
    curl -fsS "http://node$i:80/Stats" || echo "down"
    echo
  done
  sleep 1
done
