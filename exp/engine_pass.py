"""Standalone engine pass-cost probe at live-node shapes.
Feeds a realistic n-node gossip DAG to IncrementalEngine in sync-sized
batches and reports synced per-phase costs per pass, for different
k_capacity presizes and batch sizes."""
import os, sys, time
sys.path.insert(0, "/root/repo")
import numpy as np

def main(n=4, e_tot=20000, bs=256, cap=65536, kcap=65536, timers=True):
    import jax
    CACHE_DIR = os.path.join(os.path.expanduser("~"), ".cache", "babble_tpu", "jax")
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    from babble_tpu.ops.dag import synthetic_dag
    from babble_tpu.ops.incremental import IncrementalEngine
    dag, _ = synthetic_dag(n, e_tot, seed=5)
    if timers:
        os.environ["BABBLE_ENGINE_TIMERS"] = "1"
    eng = IncrementalEngine(n, capacity=cap, block=512, k_capacity=kcap)
    k = 0
    per = []
    while k < e_tot:
        hi = min(k + bs, e_tot)
        eng.append_batch(dag.self_parent[k:hi], dag.other_parent[k:hi],
                         dag.creator[k:hi], dag.index[k:hi], dag.coin[k:hi],
                         np.arange(k, hi, dtype=np.int64) * 1000 + 1_700_000_000_000_000_000)
        t0 = time.perf_counter()
        eng.run()
        dt = time.perf_counter() - t0
        per.append((dt, dict(eng.phase_ns)))
        k = hi
    total = sum(d for d, _ in per)
    print(f"   total run-pass wall: {total:.1f}s")
    # steady state = last half
    half = per[len(per) // 2:]
    med = np.median([d for d, _ in half])
    print(f"[n={n} cap={cap} kcap={kcap} bs={bs}] passes={len(per)} "
          f"steady median {med*1e3:.1f} ms/pass -> {bs/med:,.0f} ev/s")
    agg = {}
    for _, ph in half:
        for name, ns in ph.items():
            agg.setdefault(name, []).append(ns / 1e6)
    for name, vals in sorted(agg.items(), key=lambda kv: -np.median(kv[1])):
        print(f"   {name:12s} median {np.median(vals):7.1f} ms  max {max(vals):7.1f}")
    cons = int((eng.rr[:e_tot] >= 0).sum())
    print(f"   consensus events: {cons}")

if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--e", type=int, default=20000)
    ap.add_argument("--bs", type=int, default=256)
    ap.add_argument("--cap", type=int, default=65536)
    ap.add_argument("--kcap", type=int, default=65536)
    ap.add_argument("--no-timers", action="store_true")
    a = ap.parse_args()
    main(a.n, a.e, a.bs, a.cap, a.kcap, not a.no_timers)
