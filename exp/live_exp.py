"""Instrumented live-testnet experiment: like bench.node_testnet_events_per_sec
but dumps per-node phase breakdowns so we can see where the one core goes."""
import os, sys, time, threading, json
sys.path.insert(0, "/root/repo")
if os.environ.get("SWITCH_IV"):
    sys.setswitchinterval(float(os.environ["SWITCH_IV"]))

def main(engine="tpu", n_nodes=4, warm_s=150.0, window_s=45.0, interval=1.0,
         gate=1500):
    import jax as _jax
    CACHE_DIR = os.path.join(os.path.expanduser("~"), ".cache", "babble_tpu", "jax")
    os.makedirs(CACHE_DIR, exist_ok=True)
    _jax.config.update("jax_compilation_cache_dir", CACHE_DIR)

    from babble_tpu import crypto
    from babble_tpu.hashgraph import InmemStore
    from babble_tpu.net import InmemTransport, Peer
    from babble_tpu.net.inmem_transport import connect_all
    from babble_tpu.node import Node
    from babble_tpu.node.config import test_config

    from babble_tpu.proxy import InmemAppProxy

    keys = [crypto.key_from_seed(9000 + i) for i in range(n_nodes)]
    entries = []
    for i, k in enumerate(keys):
        pub_hex = "0x" + crypto.pub_key_bytes(k).hex().upper()
        entries.append((k, Peer(f"addr{i}", pub_hex)))
    entries.sort(key=lambda kp: kp[1].pub_key_hex)
    transports = [InmemTransport(p.net_addr, timeout=2.0) for _, p in entries]
    connect_all(transports)
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = test_config(heartbeat=0.01, cache_size=100000)
        conf.engine = engine
        conf.consensus_interval = interval
        node = Node(conf, i, key, peers, InmemStore(participants, 100000),
                    transports[i], InmemAppProxy())
        node.init()
        nodes.append(node)

    stop = threading.Event()
    def bombard():
        i = 0
        while not stop.is_set():
            try:
                nodes[i % n_nodes].submit_tx(f"bench tx {i}".encode())
            except Exception:
                pass
            i += 1
            time.sleep(0.002)

    committed = lambda: min(len(nd.core.get_consensus_events()) for nd in nodes)
    t_start = time.monotonic()
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        bomber = threading.Thread(target=bombard, daemon=True)
        bomber.start()
        deadline = time.monotonic() + warm_s
        while time.monotonic() < deadline and committed() < gate:
            time.sleep(0.5)
        print(f"[exp] warm done at +{time.monotonic()-t_start:.1f}s committed={committed()}", flush=True)
        # snapshot phase counters
        snap0 = [dict((k, list(v)) for k, v in list(nd.core.phase_ns.items())) for nd in nodes]
        c0, t0 = committed(), time.monotonic()
        time.sleep(window_s)
        c1, t1 = committed(), time.monotonic()
        snap1 = [dict((k, list(v)) for k, v in list(nd.core.phase_ns.items())) for nd in nodes]
    finally:
        stop.set()
        for nd in nodes:
            nd.shutdown()
    dt = t1 - t0
    eps = (c1 - c0) / dt
    print(f"[exp] engine={engine} n={n_nodes} interval={interval}: {eps:.1f} ev/s ({c1-c0} in {dt:.1f}s)")
    # aggregate per-phase deltas across nodes
    agg = {}
    for s0, s1 in zip(snap0, snap1):
        for ph, v1 in s1.items():
            v0 = s0.get(ph, [0, 0, 0])
            agg.setdefault(ph, [0.0, 0])
            agg[ph][0] += (v1[1] - v0[1]) / 1e9
            agg[ph][1] += v1[2] - v0[2]
    print(f"[exp] phase totals over {dt:.1f}s window (all {n_nodes} nodes), core-seconds:")
    for ph, (secs, calls) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        print(f"  {ph:24s} {secs:7.2f}s  calls={calls:6d}  ({secs/dt*100:5.1f}% of wall)")
    ins = sum(nd.core.hg.topological_index for nd in nodes)
    print(f"[exp] total events inserted (all nodes, lifetime): {ins}")
    for i, nd in enumerate(nodes):
        eng = getattr(nd.core.hg, "engine", None)
        if eng is not None:
            print(f"[exp] node{i} windows: {getattr(eng, '_dbg_windows', None)} "
                  f"e={eng.e} und={int((eng.rr[:eng.e] < 0).sum())} "
                  f"rounds={len(eng._fr_table)}+{eng.rho_min}")
    return eps

if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--engine", default="tpu")
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--warm", type=float, default=150.0)
    ap.add_argument("--window", type=float, default=45.0)
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--gate", type=int, default=1500)
    a = ap.parse_args()
    main(a.engine, a.n, a.warm, a.window, a.interval, a.gate)
