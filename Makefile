# Build/CI entry points — reference makefile:24-25 (`make test`) plus
# the bench and demo-testnet drivers, and `make dist` as the
# counterpart of the reference's release build (scripts/dist.sh).
PY ?= python

.PHONY: test test-fast test-crash bench demo conf run bombard watch stop dist

dist:
	$(PY) -m build

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -m "not slow"

test-crash:
	$(PY) -m pytest tests/test_crash.py tests/test_durability.py -q

bench:
	$(PY) bench.py

demo:
	demo/scripts/demo.sh

conf:
	demo/scripts/conf.sh

run:
	demo/scripts/run-testnet.sh

bombard:
	demo/scripts/bombard.sh

watch:
	demo/scripts/watch.sh

stop:
	demo/scripts/stop.sh
