#!/usr/bin/env python
"""Benchmark: consensus throughput of the batched TPU engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "events/s", "vs_baseline": N}

Baseline: the reference Go implementation's published steady-state
gossip throughput — 265.53-268.27 events/s to consensus on a 4-node
docker testnet (reference docs/usage.rst:31-34); we compare against the
midpoint 266.9. The benchmark drives the flagship jitted pipeline
(divide rounds -> decide fame -> find order, babble_tpu/ops) over a
synthetic random-gossip DAG at N=64 peers — 16x the reference's peer
count — and reports events/sec to full consensus order, including the
host-side final sort.

Extra context (host-engine comparison, other sizes) goes to stderr;
the driver consumes only the stdout JSON line.
"""

import json
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def time_pipeline(dag, s_rank, warm=1, reps=3):
    from babble_tpu.ops.pipeline import run_pipeline

    for _ in range(warm):
        out = run_pipeline(dag)
        out[0].block_until_ready()
    best = float("inf")
    result = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run_pipeline(dag)
        rounds, wit, wt, famous, rr, cts = [np.asarray(x) for x in out]
        # host finish: the consensus total order (rr, ts, S-tiebreak)
        mask = rr >= 0
        order = np.lexsort((s_rank[mask], cts[mask], rr[mask]))
        dt = time.perf_counter() - t0
        if dt < best:
            best = dt
            result = (rounds, rr, mask, order)
    return best, result


def host_engine_events_per_sec(n_peers=4, n_events=600, seed=7):
    """Reference-semantics host engine on real signed events, for the
    stderr comparison line."""
    import random

    from babble_tpu import crypto
    from babble_tpu.gojson import Timestamp
    from babble_tpu.hashgraph import Event, Hashgraph, InmemStore

    rng = random.Random(seed)
    keys = [crypto.key_from_seed(3000 + i) for i in range(n_peers)]
    pubs = [crypto.pub_key_bytes(k) for k in keys]
    participants = {"0x" + p.hex().upper(): i for i, p in enumerate(pubs)}
    clock = [1_700_000_000_000_000_000]
    heads = [""] * n_peers
    seqs = [-1] * n_peers
    events = []

    def make(i, op):
        clock[0] += 1_000_000
        seqs[i] += 1
        ev = Event.new([b"tx"], [heads[i], op], pubs[i], seqs[i],
                       timestamp=Timestamp(clock[0]))
        ev.sign(keys[i])
        heads[i] = ev.hex()
        events.append(ev)

    for i in range(n_peers):
        make(i, "")
    for _ in range(n_events - n_peers):
        i = rng.randrange(n_peers)
        j = rng.choice([x for x in range(n_peers) if x != i])
        make(i, heads[j])

    h = Hashgraph(participants, InmemStore(participants, 2 * n_events))
    t0 = time.perf_counter()
    for ev in events:
        h.insert_event(ev, True)
    h.run_consensus()
    dt = time.perf_counter() - t0
    done = len(h.consensus_events())
    return done / dt, done


def main():
    from babble_tpu.ops.dag import synthetic_dag

    n, e = 64, 50_000
    t_gen = time.perf_counter()
    dag, s_rank = synthetic_dag(n, e, seed=1, max_level_width=512)
    log(f"synthetic DAG: n={n} e={e} levels={dag.levels.shape} "
        f"gen={time.perf_counter()-t_gen:.2f}s")

    best, (rounds, rr, mask, order) = time_pipeline(dag, s_rank)
    n_consensus = int(mask.sum())
    ev_per_s = n_consensus / best
    log(f"batched engine: {best*1e3:.1f} ms -> {n_consensus} consensus events "
        f"({ev_per_s:,.0f} events/s), last round {int(rounds.max())}")

    try:
        host_eps, host_done = host_engine_events_per_sec()
        log(f"host engine (4 peers, real events): {host_eps:,.0f} events/s "
            f"({host_done} consensus events)")
    except Exception as exc:  # noqa: BLE001 - bench context only
        log(f"host engine comparison skipped: {exc}")

    baseline = 266.9
    print(json.dumps({
        "metric": "consensus_events_per_s_n64",
        "value": round(ev_per_s, 1),
        "unit": "events/s",
        "vs_baseline": round(ev_per_s / baseline, 1),
    }))


if __name__ == "__main__":
    main()
