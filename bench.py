#!/usr/bin/env python
"""Benchmark: consensus throughput of the batched TPU engine.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "events/s", "vs_baseline": N, ...}

Robustness contract (the round-2 bench died to a transiently-Unavailable
TPU backend and an unbounded run): the parent process never imports JAX.
It probes the backend in a subprocess with a hard timeout and bounded
retries, runs the measurement in a budgeted subprocess, keeps the last
partial result the child reported, and ALWAYS emits the stdout JSON
line — with an "error" field when something failed and a CPU fallback
when the TPU never comes up.

Metric: events/sec to full consensus order (device pipeline + host
final sort) at N=64 peers over a 50k-event synthetic random-gossip DAG
— the event pattern the gossip runtime produces (reference
node/node.go:315-487). `vs_baseline` is the honest like-for-like
multiple: this repo's own reference-semantics host engine on the same
topology (real signed events, ECDSA verify on insert, same gossip
pattern). The reference's published 4-node docker steady state
(265.53-268.27 ev/s, reference docs/usage.rst:31-34) is reported
separately as `ref_docker_events_per_s` — an indicative, not
like-for-like, anchor.

Stages (each emits a partial JSON line; later stages refine):
  smoke     n=8    e=256     proves the pipeline end-to-end
  headline  n=64   e=50_000  the reported metric
  northstar n=1024 e=100_000 BASELINE.md driver target size
  host      n=64   same topology subset -> vs_baseline denominator
"""

import json
import os
import subprocess
import sys
import time

BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1500"))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "150"))
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
CACHE_DIR = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR", "/tmp/babble_tpu_jax_cache"
)

_T0 = time.monotonic()


def log(msg):
    print(f"[bench +{time.monotonic() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


# --------------------------------------------------------------------------
# Parent: probe + budgeted child + guaranteed JSON emission.
# --------------------------------------------------------------------------

_PROBE_SRC = (
    "import jax, json;"
    "d = jax.devices();"
    "print(json.dumps({'backend': jax.default_backend(), 'n': len(d),"
    " 'kind': d[0].device_kind}))"
)


def probe_backend():
    """Can a fresh process initialize the configured JAX backend? The
    axon TPU tunnel is transiently Unavailable and sometimes hangs in
    init (observed >8 min), so each attempt is a subprocess with a hard
    timeout."""
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        t0 = time.monotonic()
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
            )
            if out.returncode == 0 and out.stdout.strip():
                info = json.loads(out.stdout.strip().splitlines()[-1])
                log(f"backend probe ok in {time.monotonic() - t0:.1f}s: {info}")
                return info
            log(f"probe attempt {attempt}/{PROBE_ATTEMPTS} rc={out.returncode}"
                f" stderr: ...{out.stderr.strip()[-300:]}")
        except subprocess.TimeoutExpired:
            log(f"probe attempt {attempt}/{PROBE_ATTEMPTS} timed out"
                f" after {PROBE_TIMEOUT_S:.0f}s")
        except Exception as exc:  # noqa: BLE001
            log(f"probe attempt {attempt}/{PROBE_ATTEMPTS} failed: {exc}")
        time.sleep(min(5.0 * attempt, 20.0))
    return None


def run_child(env, timeout):
    """Run the measurement child; return (last partial payload, error)."""
    env = dict(env)
    # Let the child's budget clock account for parent time already spent.
    env["BENCH_T0_OFFSET"] = str(time.monotonic() - _T0)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE, stderr=sys.stderr, text=True, env=env,
    )
    # A hanging child produces no stdout, and readline() would block
    # past any deadline — so a reader thread drains stdout while the
    # parent enforces the budget on proc.wait().
    import threading

    results = []

    def drain():
        for line in proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                results.append(json.loads(line))
            except json.JSONDecodeError:
                log(f"child emitted non-JSON stdout: {line[:200]}")

    reader = threading.Thread(target=drain, daemon=True)
    reader.start()
    err = None
    try:
        rc = proc.wait(timeout=timeout)
        if rc != 0:
            err = f"child exited rc={rc}"
    except subprocess.TimeoutExpired:
        err = f"child exceeded budget ({timeout:.0f}s), killed"
        proc.kill()
        proc.wait()
    except Exception as exc:  # noqa: BLE001
        err = f"child failed: {exc}"
        proc.kill()
        proc.wait()
    reader.join(timeout=5.0)
    return (results[-1] if results else None), err


def _cpu_env(env):
    """CPU-only child env. JAX_PLATFORMS=cpu alone is not enough: the
    environment's sitecustomize registers (and dials) the axon PJRT
    plugin whenever PALLAS_AXON_POOL_IPS is set, and that dial is what
    hangs when the tunnel is down — so the trigger var must go too."""
    env = dict(env)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def main():
    env = os.environ.copy()
    env["JAX_COMPILATION_CACHE_DIR"] = CACHE_DIR
    os.makedirs(CACHE_DIR, exist_ok=True)

    info = probe_backend()
    fallback = None
    if info is None:
        fallback = "configured backend unreachable; fell back to CPU"
        log(fallback)
        env = _cpu_env(env)
        info = {"backend": "cpu", "n": 1, "kind": "fallback-cpu"}

    child_budget = BUDGET_S - (time.monotonic() - _T0) - 10.0
    payload, err = run_child(env, max(child_budget, 60.0))

    if (payload is None or not payload.get("value")) and fallback is None:
        # TPU probe passed but the run died/hung before producing a
        # headline number: one CPU retry with whatever budget remains,
        # so the round still gets a number. Backend labels are only
        # switched if the retry's payload is actually the one kept.
        log(f"no headline result from backend run ({err}); retrying on CPU")
        retry_budget = BUDGET_S - (time.monotonic() - _T0) - 5.0
        if retry_budget > 60.0:
            retry_payload, retry_err = run_child(_cpu_env(env), retry_budget)
            if retry_payload is not None and (
                payload is None or retry_payload.get("value")
            ):
                payload = retry_payload
                info = {"backend": "cpu", "n": 1, "kind": "fallback-cpu"}
                fallback = (f"tpu run produced no headline number ({err}); "
                            "CPU fallback")
                err = retry_err

    if payload is None:
        payload = {
            "metric": "consensus_events_per_s_n64",
            "value": 0.0,
            "unit": "events/s",
            "vs_baseline": 0.0,
        }
    payload.setdefault("backend", info.get("backend"))
    payload["device_kind"] = info.get("kind")
    notes = [x for x in (fallback, err) if x]
    if notes:
        payload["error"] = "; ".join(dict.fromkeys(notes))
    payload["wall_s"] = round(time.monotonic() - _T0, 1)
    print(json.dumps(payload), flush=True)


# --------------------------------------------------------------------------
# Child: the actual measurement. Emits a (partial) JSON line after every
# completed stage so a mid-run kill still leaves the best result so far.
# --------------------------------------------------------------------------


def _emit(payload):
    print(json.dumps(payload), flush=True)


def _budget_left():
    offset = float(os.environ.get("BENCH_T0_OFFSET", "0"))
    return BUDGET_S - offset - (time.monotonic() - _T0) - 30.0


def time_pipeline(dag, s_rank, warm=1, reps=3, engine="auto"):
    """Times `reps` full runs; returns (best, median, times, n_consensus,
    max_round). The chip is shared and tunneled (observed +/-40%
    run-to-run), so median-with-spread is the honest number and best is
    reported alongside, never alone."""
    import numpy as np

    from babble_tpu.ops.pipeline import run_pipeline

    t0 = time.monotonic()
    for _ in range(warm):
        out = run_pipeline(dag, engine=engine)
        np.asarray(out[0])
    log(f"  [{engine}] compile+warmup {time.monotonic() - t0:.1f}s")
    times = []
    n_consensus = 0
    max_round = 0
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run_pipeline(dag, engine=engine)
        rounds, wit, wt, famous, rr, cts = [np.asarray(x) for x in out]
        # host finish: the consensus total order (rr, ts, S-tiebreak)
        mask = rr >= 0
        np.lexsort((s_rank[mask], cts[mask], rr[mask]))
        times.append(time.perf_counter() - t0)
        n_consensus = int(mask.sum())
        max_round = int(rounds.max())
    return (min(times), float(np.median(times)), times, n_consensus,
            max_round)


def tune_engine(dag, s_rank):
    """Time both pipeline engines once and return the faster — the
    closure/frontier path is built for the MXU, the wavefront for
    dispatch-cheap backends; measuring beats guessing on an unknown
    chip."""
    results = {}
    for engine in ("closure", "wavefront"):
        if _budget_left() < 60:
            break
        try:
            best, _, _, _, _ = time_pipeline(dag, s_rank, warm=1, reps=1,
                                             engine=engine)
            results[engine] = best
            log(f"  tune: {engine} {best * 1e3:.1f} ms")
        except Exception as exc:  # noqa: BLE001
            log(f"  tune: {engine} failed: {exc}")
    if not results:
        return "auto"
    return min(results, key=results.get)


def host_engine_events_per_sec(n_peers, n_events, seed=7):
    """This repo's reference-semantics host engine on real signed
    events with the same gossip topology — the honest like-for-like
    baseline."""
    from babble_tpu import crypto
    from babble_tpu.gojson import Timestamp
    from babble_tpu.hashgraph import Event, Hashgraph, InmemStore
    import numpy as np

    rng = np.random.default_rng(seed)
    keys = [crypto.key_from_seed(3000 + i) for i in range(n_peers)]
    pubs = [crypto.pub_key_bytes(k) for k in keys]
    participants = {"0x" + p.hex().upper(): i for i, p in enumerate(pubs)}
    clock = 1_700_000_000_000_000_000
    heads = [""] * n_peers
    seqs = [-1] * n_peers
    events = []

    creators = np.concatenate(
        [np.arange(n_peers), rng.integers(0, n_peers, size=n_events - n_peers)]
    )
    others = rng.integers(1, n_peers, size=n_events)
    for i in range(n_events):
        c = int(creators[i])
        op = heads[(c + int(others[i])) % n_peers] if i >= n_peers else ""
        clock += 1_000_000
        seqs[c] += 1
        ev = Event.new([b"tx"], [heads[c], op], pubs[c], seqs[c],
                       timestamp=Timestamp(clock))
        ev.sign(keys[c])
        heads[c] = ev.hex()
        events.append(ev)

    h = Hashgraph(participants, InmemStore(participants, 2 * n_events))
    t0 = time.perf_counter()
    for ev in events:
        h.insert_event(ev, True)
    h.run_consensus()
    dt = time.perf_counter() - t0
    return len(h.consensus_events()) / dt, len(h.consensus_events()), dt


def _audit_metrics_scrape(node, phases, file_store=False):
    """Scrape a live node's /metrics over real HTTP, run it through
    the exposition parser, and FAIL (raise) when a core series is
    missing — the CI node-smoke job runs this so a telemetry
    regression breaks the build, not the next incident. Also loads
    /debug/trace and checks it is valid Chrome trace JSON."""
    import urllib.request

    from babble_tpu.service import Service
    from babble_tpu.telemetry import promtext

    svc = Service("127.0.0.1:0", node)
    svc.serve_async()
    try:
        with urllib.request.urlopen(
                f"http://{svc.addr}/metrics", timeout=10) as r:
            text = r.read().decode()
        samples, _types = promtext.parse(text)  # raises on bad format
        required = [
            "babble_commit_latency_seconds",
            "babble_gossip_rtt_seconds",
            "babble_breaker_state",
            "babble_engine_pass_seconds",
            "babble_sync_requests_total",
            "babble_phase_seconds",
            # Consensus health plane (docs/observability.md
            # "Consensus health"): divergence/fork counters exist (at
            # zero) from boot, progress + stall gauges refresh at
            # scrape, the trace ring reports drops.
            "babble_divergence_total",
            "babble_forks_total",
            "babble_round_lag",
            "babble_undecided_witnesses",
            "babble_last_decided_fame_round",
            "babble_consensus_stalled",
            "babble_chain_index",
            "babble_trace_dropped_total",
            # Gossip efficiency observatory (docs/observability.md
            # "Gossip efficiency"): redundancy accounting counters and
            # the propagation-latency histogram — aggregate children
            # exist (at zero) from boot, per-peer ones as soon as a
            # sync lands.
            "babble_gossip_offered_events_total",
            "babble_gossip_new_events_total",
            "babble_gossip_duplicate_events_total",
            "babble_gossip_stale_events_total",
            "babble_gossip_syncs_total",
            "babble_gossip_payload_bytes_total",
            "babble_propagation_latency_seconds",
            # Saturation observatory (docs/observability.md
            # "Saturation"): every bounded buffer exports depth/
            # capacity/wait/drops from boot, and the thread CPU
            # attribution gauges refresh at scrape.
            "babble_queue_depth",
            "babble_queue_capacity",
            "babble_queue_wait_seconds",
            "babble_queue_dropped_total",
            "babble_thread_cpu_seconds_total",
            "babble_cpu_utilization_cores",
            "babble_cpu_saturation_ratio",
            # Crypto plane (docs/observability.md "Crypto plane"):
            # the backend info gauge and the per-call batch-size
            # histogram exist as soon as the first sync batch is
            # ECDSA-checked; verified-event totals from boot.
            "babble_verify_backend",
            "babble_verify_batch_size",
            "babble_verify_events_total",
            # Ingress armor (docs/ingress.md): admission counters
            # exist (at zero) from boot, and the intake queue reports
            # through the standard queue families.
            "babble_ingress_admitted_total",
            "babble_ingress_shed_total",
            "babble_ingress_quota_rejected_total",
            'babble_queue_depth{queue="intake"}',
            # Capacity observatory (docs/observability.md "Capacity"):
            # per-subsystem retained bytes, the process RSS ground
            # truth, cache efficiency, and the cardinality self-audit
            # all refresh at scrape.
            "babble_mem_bytes",
            'babble_mem_bytes{component="store_event_log"}',
            "babble_process_rss_bytes",
            "babble_mem_budget_bytes",
            'babble_cache_hits_total{cache="store_events"}',
            "babble_telemetry_series",
            "babble_telemetry_series_total",
        ]
        if file_store:
            required.append("babble_store_fsync_seconds")
            required.append('babble_store_bytes{file="wal"}')
        missing = promtext.check_series(samples, required)
        if missing:
            raise RuntimeError(
                f"/metrics scrape is missing core series: {missing}")
        with urllib.request.urlopen(
                f"http://{svc.addr}/debug/trace", timeout=10) as r:
            trace = json.loads(r.read())
        if not trace.get("traceEvents"):
            raise RuntimeError("/debug/trace has no traceEvents")
        phases["metrics_scrape"] = {
            "families": len(samples),
            "trace_events": len(trace["traceEvents"]),
        }
    finally:
        svc.close()


def _runtime_arg() -> str:
    """`--runtime threads|procs` / BENCH_RUNTIME: the execution
    runtime every bench testnet is built with (docs/runtime.md)."""
    if "--runtime" in sys.argv:
        try:
            return sys.argv[sys.argv.index("--runtime") + 1]
        except IndexError:
            pass
    return os.environ.get("BENCH_RUNTIME", "threads")


def _cpus_effective():
    """Cores this process may actually run on (None where the
    platform has no affinity API). Recorded in every soak ledger
    entry so a 1-core container's numbers are machine-distinguishable
    from a real multicore run — bench_compare auto-skips the
    multicore-only gates on it."""
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return None


def build_host_testnet(n_nodes, engine="host", interval=0.0,
                       heartbeat=0.0015, store="inmem",
                       store_sync="batch", trace_sample=0.0,
                       wire_format="columnar", transport="inmem",
                       health=True, observatory=True, plumtree=True,
                       profile_hz=0.0, admission=True, quota_rate=0.0,
                       ingress_target=0.2, runtime=None,
                       capacity=True):
    """Construct (but do not start) a localhost testnet of N real
    nodes: signed keys, fully-meshed transports, per-node stores and
    app proxies — the shared builder behind the throughput smoke, the
    overhead A/Bs, and the gossip soak (one construction path, so a
    config knob added here is measured everywhere). Returns the node
    list; callers own run_async/shutdown."""
    import tempfile

    from babble_tpu import crypto
    from babble_tpu.hashgraph import FileStore, InmemStore
    from babble_tpu.net import InmemTransport, Peer
    from babble_tpu.net.inmem_transport import connect_all
    from babble_tpu.node import Node
    from babble_tpu.node.config import test_config
    from babble_tpu.proxy import InmemAppProxy

    keys = [crypto.key_from_seed(9000 + i) for i in range(n_nodes)]
    keyed = sorted(
        ((k, "0x" + crypto.pub_key_bytes(k).hex().upper()) for k in keys),
        key=lambda kp: kp[1])
    if transport == "tcp":
        # Real localhost sockets: the configuration where the wire
        # format actually serializes (binary columnar frames vs
        # base64-inside-JSON-inside-readline) instead of passing
        # payload objects by reference.
        from babble_tpu.net import TCPTransport

        transports = [
            TCPTransport("127.0.0.1:0", timeout=2.0,
                         wire_format=wire_format, consumer_buffer=64)
            for _ in keyed]
        entries = [(k, Peer(t.local_addr(), pub))
                   for (k, pub), t in zip(keyed, transports)]
    else:
        entries = [(k, Peer(f"addr{i}", pub))
                   for i, (k, pub) in enumerate(keyed)]
        transports = [InmemTransport(p.net_addr, timeout=2.0)
                      for _, p in entries]
        connect_all(transports)
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    rt = runtime or _runtime_arg()
    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = test_config(heartbeat=heartbeat, cache_size=100000)
        conf.engine = engine
        conf.wire_format = wire_format
        # Execution runtime (docs/runtime.md): procs moves the verify
        # plane to worker processes. The pool only engages above the
        # min batch AND workers > 1 — auto would resolve to 1 on a
        # 1-core box, so the procs leg pins a real pool size (the
        # point of the leg is measuring the off-GIL path).
        conf.runtime = rt
        if rt == "procs":
            conf.verify_workers = max(2, min(8, os.cpu_count() or 1))
        # Compile the engine's kernel ladder at construction (first
        # node pays; jit caches are process-global) — this is what
        # retired the old 6000-event warm gate.
        conf.engine_prewarm = engine == "tpu"
        conf.consensus_interval = interval
        # End-to-end tx tracing sample rate (docs/observability.md) —
        # 0 keeps the stamping/flow paths as no-ops; the trace-overhead
        # A/B drives this.
        conf.trace_sample = trace_sample
        # Consensus health plane (docs/observability.md "Consensus
        # health"): sentinel + stall watchdog are the product default;
        # health=False is the baseline leg of the --health-overhead
        # A/B (no chain hashing, no piggyback, no watchdog thread).
        conf.divergence_sentinel = health
        conf.stall_timeout = 30.0 if health else 0.0
        # Gossip efficiency observatory (docs/observability.md "Gossip
        # efficiency"): redundancy accounting + creation-stamp sidecar
        # + propagation histogram; observatory=False is the baseline
        # leg of the --gossip-overhead A/B.
        conf.gossip_observatory = observatory
        # Epidemic broadcast tree (docs/gossip.md): the product default
        # since the plumtree PR; plumtree=False is the pull-only
        # baseline (the committed pre-plumtree SOAK ledger's shape).
        conf.plumtree = plumtree
        # In-process flame profiler (docs/observability.md
        # "Saturation"): 0 keeps the sampler thread unspawned — the
        # --profile-overhead A/B drives this.
        conf.profile_hz = profile_hz
        # Ingress armor (docs/ingress.md): the admission plane is the
        # product default; admission=False is the bare-intake baseline
        # leg of the --ingress-overhead A/B. quota_rate exercises the
        # per-client token buckets (the --loadgen leg drives this).
        conf.admission = admission
        conf.quota_rate = quota_rate
        conf.ingress_target_delay = ingress_target
        # Capacity observatory (docs/observability.md "Capacity"): the
        # product default; capacity=False is the baseline leg of the
        # --capacity-overhead A/B (no sizers, no growth model, hot-path
        # carry counters still incremented — they are the cheap part
        # the A/B exists to bound).
        conf.capacity = capacity
        if store == "file":
            # Durable-path A/B (docs/robustness.md "Crash recovery"):
            # same testnet over WAL-backed FileStores, so the
            # store_commit_share below measures the transactional
            # overhead against the in-mem baseline.
            sdir = tempfile.mkdtemp(prefix="bench-store-")
            node_store = FileStore(
                participants, 100000,
                os.path.join(sdir, f"node{i}.db"), sync=store_sync)
        else:
            node_store = InmemStore(participants, 100000)
        node = Node(conf, i, key, peers, node_store,
                    transports[i], InmemAppProxy())
        node.init()
        nodes.append(node)
    return nodes


def node_testnet_events_per_sec(engine="tpu", n_nodes=4, warm_s=60.0,
                                window_s=30.0, interval=None,
                                warm_gate_events=1500, windows=1,
                                store="inmem", store_sync="batch",
                                metrics_scrape=False, trace_sample=0.0,
                                wire_format="columnar", heartbeat=None,
                                transport="inmem", health=True,
                                observatory=True, profile_hz=0.0,
                                capacity=True, scrape_hz=0.0):
    """Throughput of a live localhost testnet: N real nodes (threads,
    inmem transport, signed events, full sync protocol) bombarded with
    transactions; returns (committed consensus events/sec during a
    steady-state window after a warmup, per-phase breakdown dict) —
    the breakdown aggregates every node's Core.phase_ns so a
    regression in this stage is attributable to a phase (the sustained
    stage alone had this before). The reference's counterpart is the
    4-node docker demo steady state (reference docs/usage.rst:31-34)."""
    import threading

    if engine == "tpu":
        import jax as _jax

        # The persistent compile cache is the product default (cli.py
        # enables it for every tpu-engine node); without it the warmup
        # re-pays every engine-shape compile and the window lands in
        # the immature phase. child() also sets this, but the function
        # must be self-sufficient for standalone calls (verification
        # drives import bench and call it directly). Host-engine runs
        # never touch JAX, so the --node-smoke CI path stays light.
        os.makedirs(CACHE_DIR, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
        _jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)

    if heartbeat is None:
        # Host-engine gossip is bounded by round cadence once ingest is
        # cheap (columnar wire + libcrypto ECDSA): each round yields ~2
        # events, so the heartbeat IS the throughput ceiling. 1.5 ms
        # keeps the cluster comfortably inside what the ingest path
        # sustains (A/B'd 0.01 -> 0.0015: 433 -> 794 ev/s on a 1-core
        # runner); the tpu engine keeps the 10 ms cadence that paces
        # its device passes.
        heartbeat = 0.01 if engine == "tpu" else 0.0015
    # Batch many syncs per consensus pass. For the tpu engine each
    # pass costs a ~110 ms tunnel round trip and the nodes share
    # one chip, so a 1 s cadence keeps the tunnel under 50% duty
    # (0.25 s oversubscribed it, A/B 68 vs 240 ev/s). For the
    # 16-node host testnet the same batching amortizes the
    # undecided-round rescan (A/B 52 vs 78 ev/s); the 4-node host
    # testnet keeps the reference's per-sync cadence.
    if interval is None:
        # tpu: the FLOOR of the adaptive cadence (the worker
        # tracks ~3x its measured pass wall, see node.py
        # _consensus_loop).
        interval = 0.25 if engine == "tpu" else 0.0
    nodes = build_host_testnet(
        n_nodes, engine=engine, interval=interval, heartbeat=heartbeat,
        store=store, store_sync=store_sync, trace_sample=trace_sample,
        wire_format=wire_format, transport=transport, health=health,
        observatory=observatory, profile_hz=profile_hz,
        capacity=capacity)

    stop = threading.Event()
    # One process, dozens of pure-Python threads: the default 5 ms GIL
    # switch interval thrashes caches (A/B at 16 nodes: 78 -> 102 ev/s
    # at 100 ms). Restored in the finally below.
    import sys as _sys
    old_switch = _sys.getswitchinterval()
    _sys.setswitchinterval(0.1)

    def bombard():
        i = 0
        while not stop.is_set():
            try:
                nodes[i % n_nodes].submit_tx(f"bench tx {i}".encode())
            except Exception:  # noqa: BLE001
                pass
            i += 1
            time.sleep(0.002)

    def scraper():
        # Simulated Prometheus: refresh every scrape-time gauge at a
        # fixed cadence so an A/B leg pays what a scraped production
        # node pays (the capacity sizers only run when scraped).
        while not stop.is_set():
            try:
                nodes[0].get_stats()
            except Exception:  # noqa: BLE001
                pass
            stop.wait(1.0 / scrape_hz)

    committed = lambda: min(  # noqa: E731
        len(nd.core.get_consensus_events()) for nd in nodes)
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        bomber = threading.Thread(target=bombard, daemon=True)
        bomber.start()
        if scrape_hz > 0:
            threading.Thread(target=scraper, daemon=True).start()
        # Warmup gate: the tunneled runtime compiles each engine shape
        # per process (~2 min for the live-node presize at small n; the
        # persistent cache does not cover this backend), and the first
        # post-compile minutes still hit occasional window-growth
        # compiles — so the gate requires enough committed events to
        # prove MATURE steady state, under a generous cap.
        deadline = time.monotonic() + warm_s
        while time.monotonic() < deadline and committed() < warm_gate_events:
            time.sleep(0.5)
        # Commit-latency snapshot at window start: the p50/p99 below is
        # a DELTA over the measurement windows (warmup samples — cold
        # caches, first compiles — would otherwise poison the tail),
        # merged across every node's submit->commit histogram.
        lat0 = [nd._m_commit_latency.snapshot() for nd in nodes]
        # Median over `windows` measurement windows: a single window is
        # at the mercy of transient tunnel stalls (observed: a 62s
        # stall inside an otherwise 5.6s-rep run tanked one window 2.5x
        # below back-to-back A/Bs of the same build).
        rates = []
        for _ in range(windows):
            c0, t0 = committed(), time.monotonic()
            time.sleep(window_s)
            c1, t1 = committed(), time.monotonic()
            if c1 > c0:
                rates.append((c1 - c0) / (t1 - t0))
            # c1 <= c0: a lagging node fast-forwarded (store reset,
            # node.py _fast_forward) or the chip stalled — skip the
            # window.
        lat = None
        for nd, before in zip(nodes, lat0):
            delta = nd._m_commit_latency.snapshot() - before
            lat = delta if lat is None else lat.merge(delta)
        # Per-phase breakdown (harvested before shutdown): node-level
        # phases and, for the device engine, its sub-phases. The
        # engine_* entries are subsets of consensus_dispatch/collect
        # wall, so they get their own share denominator; engine_overlap
        # is not host wall at all (device compute that overlapped
        # ingest) and rides along in seconds.
        tot: dict = {}
        for nd in nodes:
            for ph, ent in list(nd.core.phase_ns.items()):
                tot[ph] = tot.get(ph, 0) + ent[1]
        phases: dict = {}
        # The ingest stages (docs/ingest.md) are sub-spans of `sync`,
        # so they get their own share denominator (the sync wall) and
        # stay out of the top-level split, like the engine_* subset of
        # consensus_dispatch/collect.
        # wire_unpack is a sub-span of from_wire (columnar batches
        # only); wire_pack is the outbound marshal on the diff/serve
        # side and stays top-level (docs/ingest.md "marshal split").
        ingest = {ph: v for ph, v in tot.items()
                  if ph in ("from_wire", "wire_unpack", "verify",
                            "insert")}
        # verify_<backend> re-records the verify interval keyed by the
        # crypto backend (docs/ingest.md "Crypto plane") — keep it out
        # of every share denominator or verify wall counts twice.
        top = {ph: v for ph, v in tot.items()
               if not ph.startswith("engine_") and ph not in ingest
               and not ph.startswith("verify_")
               and ph != "store_commit"}
        if top:
            s = sum(top.values())
            phases["phase_share"] = {
                ph: round(v / s, 3) for ph, v in sorted(top.items())}
        if ingest and tot.get("sync"):
            phases["ingest_phase_share"] = {
                ph: round(v / tot["sync"], 3)
                for ph, v in sorted(ingest.items())}
        # c_pull_wait/c_pull_xfer are sub-spans of c_pull (the wait/
        # transfer split) — they ride along as their own ratio and stay
        # out of the share denominator, which would double-count them.
        eng_t = {ph[len("engine_"):]: v for ph, v in tot.items()
                 if ph.startswith("engine_") and ph != "engine_overlap"
                 and not ph.startswith("engine_c_pull_")}
        if eng_t:
            es = sum(eng_t.values())
            phases["engine_phase_share"] = {
                ph: round(v / es, 3) for ph, v in sorted(eng_t.items())}
            phases["engine_pull_share"] = round(
                (eng_t.get("c_pull", 0) + eng_t.get("coords", 0)
                 + eng_t.get("fd_fold", 0)) / es, 3)
            if tot.get("engine_c_pull"):
                phases["engine_c_pull_split"] = {
                    "wait": round(tot.get("engine_c_pull_wait", 0)
                                  / tot["engine_c_pull"], 3),
                    "xfer": round(tot.get("engine_c_pull_xfer", 0)
                                  / tot["engine_c_pull"], 3)}
        if "engine_overlap" in tot:
            phases["engine_overlap_s"] = round(
                tot["engine_overlap"] / 1e9, 2)
        if "store_commit" in tot and top:
            # Durable-commit wall (sqlite COMMIT = WAL write + fsync,
            # a sub-span of sync/run_consensus) as a share of the
            # top-level phase wall: what the durable path costs vs
            # in-mem.
            phases["store_commit_share"] = round(
                tot["store_commit"] / sum(top.values()), 3)
        if lat is not None and lat.count > 0:
            # End-to-end submit->commit latency over the measurement
            # windows, cross-node (docs/observability.md).
            phases["commit_latency_p50_ms"] = round(
                lat.quantile(0.5) * 1000.0, 2)
            phases["commit_latency_p99_ms"] = round(
                lat.quantile(0.99) * 1000.0, 2)
            phases["commit_latency_samples"] = lat.count
        if metrics_scrape:
            _audit_metrics_scrape(nodes[0], phases,
                                  file_store=(store == "file"))
    finally:
        _sys.setswitchinterval(old_switch)
        stop.set()
        for nd in nodes:
            nd.shutdown()
    if not rates:
        raise RuntimeError(
            "testnet made no valid measurement window (fast-forward "
            "resets or stalls)")
    rates.sort()
    m = len(rates)
    # true median: even counts average the middle pair (an
    # upper-middle pick would report the best window after a skip).
    if m % 2:
        return rates[m // 2], phases
    return (rates[m // 2 - 1] + rates[m // 2]) / 2.0, phases


def wire_ingest_microbench(target_events=1500):
    """Columnar-vs-legacy wire A/B on the batch shape where marshal
    actually matters: one big sync diff (catch-up / eager-push shape),
    measured end to end on one core — sender pack+serialize, then
    receiver deserialize + `Core.sync` (materialize, ECDSA verify,
    insert). Live 3-node testnets at steady state move 2-4 events per
    batch, where syscalls and round-trip pacing dominate and the two
    forms tie; this is the payload-bound regime the columnar wire was
    built for (docs/ingest.md "Wire layout")."""
    import json as _json

    from babble_tpu import crypto
    from babble_tpu.hashgraph.inmem_store import InmemStore
    from babble_tpu.net.columnar import ColumnarEvents
    from babble_tpu.net.transport import SyncResponse
    from babble_tpu.node.core import Core

    keys = sorted(
        (crypto.key_from_seed(9000 + i) for i in range(3)),
        key=lambda k: crypto.pub_key_bytes(k).hex().upper())
    parts = {"0x" + crypto.pub_key_bytes(k).hex().upper(): i
             for i, k in enumerate(keys)}

    donors = [Core(i, k, parts, InmemStore(parts, 100000))
              for i, k in enumerate(keys)]
    for c in donors:
        c.init()
    import itertools

    pairs = list(itertools.permutations(range(3), 2))
    i = 0
    while sum(donors[0].known().values()) < target_events:
        a, b = pairs[i % len(pairs)]
        diff = donors[b].diff(donors[a].known())
        donors[a].add_transactions([b"wire bench tx %d" % i])
        donors[a].sync(donors[b].to_wire_batch(diff, "columnar"))
        i += 1
    diff = donors[0].diff({i: -1 for i in range(3)})

    out = {"batch_events": len(diff)}

    def fresh():
        return Core(9, keys[0], parts, InmemStore(parts, 100000))

    # The timed windows are ~200 ms; a generational GC pass over the
    # garbage a preceding testnet leg left behind would eat half a
    # window (observed 2.8x swings inside the full smoke). Collect
    # now, then keep the collector out of the measurement.
    import gc

    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        buf = donors[0].to_wire_batch(diff, "columnar").encode()
        out["pack_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        out["bytes"] = len(buf)
        c = fresh()
        t0 = time.perf_counter()
        c.sync(ColumnarEvents.decode(buf))
        dt = time.perf_counter() - t0
        out["events_per_s"] = round(len(diff) / dt, 1)

        from babble_tpu.net.tcp_transport import _b64_bytes

        t0 = time.perf_counter()
        resp = SyncResponse(
            1, events=donors[0].to_wire_batch(diff, "gojson"))
        data = _json.dumps(resp.to_dict(), default=_b64_bytes).encode()
        out["legacy_pack_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        out["legacy_bytes"] = len(data)
        c = fresh()
        t0 = time.perf_counter()
        c.sync(SyncResponse.from_dict(_json.loads(data)).events)
        dt = time.perf_counter() - t0
        out["legacy_events_per_s"] = round(len(diff) / dt, 1)
        out["bytes_ratio"] = round(out["legacy_bytes"] / out["bytes"], 2)
    finally:
        gc.enable()
    return out


def node_smoke():
    """Host-ingest microbench for CI: a 3-node in-mem host-engine
    gossip testnet (fixed seeds, no TPU, no JAX import) measured for
    ~20s, emitting one JSON line with `node_events_per_s` so host-path
    regressions are visible per-PR in the job log. The raw exit code
    is 0 whenever a measurement was made; the hard gate is
    bench_compare.py, which diffs this payload against the committed
    ledger (BENCH_SMOKE.json / BENCH_r*.json) with the
    `host_events_per_s` machine-speed calibration below normalizing
    out runner differences."""
    payload = {
        "metric": "node_events_per_s_smoke",
        "unit": "events/s",
        "nodes": 3,
        "engine": "host",
    }
    try:
        # Machine-speed calibration: the SAME pinned single-thread
        # host-engine run (n=64, e=5000, seed 7) the full bench
        # records as host_events_per_s — the shared yardstick
        # bench_compare.py uses to normalize throughput/latency
        # across machines before gating.
        calib_eps, _, _ = host_engine_events_per_sec(64, 5000)
        payload["host_events_per_s"] = round(calib_eps, 1)
        payload["host_events"] = 5000
    except Exception as exc:  # noqa: BLE001
        payload["calibration_error"] = str(exc)
    try:
        eps, phases = node_testnet_events_per_sec(
            engine="host", n_nodes=3, warm_s=8.0, window_s=12.0,
            interval=0.03, warm_gate_events=200, windows=1,
            metrics_scrape=True)
        payload["node_events_per_s"] = round(eps, 1)
        payload["node_phase_share"] = phases.get("phase_share")
        payload["node_ingest_phase_share"] = phases.get(
            "ingest_phase_share")
        payload["wire_format"] = "columnar"
        # End-to-end submit->commit latency over the measurement
        # window (docs/observability.md) — the headline observability
        # numbers next to throughput.
        payload["commit_latency_p50_ms"] = phases.get(
            "commit_latency_p50_ms")
        payload["commit_latency_p99_ms"] = phases.get(
            "commit_latency_p99_ms")
        payload["metrics_scrape"] = phases.get("metrics_scrape")
    except Exception as exc:  # noqa: BLE001
        payload["error"] = str(exc)
        _emit(payload)
        return 1
    try:
        # Columnar-vs-legacy wire A/B (docs/ingest.md): the same
        # testnet pinned to the Go-JSON event-dict payload. The delta
        # is the marshal/materialize share the packed wire removes —
        # recorded so the interop-preserving legacy path's cost stays
        # visible per-PR.
        leps, _ = node_testnet_events_per_sec(
            engine="host", n_nodes=3, warm_s=6.0, window_s=8.0,
            interval=0.03, warm_gate_events=150, windows=1,
            wire_format="gojson")
        payload["node_legacy_events_per_s"] = round(leps, 1)
        payload["wire_ab_speedup"] = round(eps / leps, 3) if leps else None
    except Exception as exc:  # noqa: BLE001
        payload["legacy_wire_error"] = str(exc)
    try:
        # Big-batch wire A/B: the payload-bound regime (catch-up /
        # eager-push diffs) where the columnar form pays — steady-state
        # testnet batches are 2-4 events, where the two forms tie.
        payload["wire_ingest"] = wire_ingest_microbench()
        payload["wire_ingest_events_per_s"] = payload["wire_ingest"][
            "events_per_s"]
    except Exception as exc:  # noqa: BLE001
        payload["wire_ingest_error"] = str(exc)
    try:
        # Cluster-scaling leg: the 16-node testnet in the same smoke,
        # so the node{3,16} trend is machine-tracked per PR (the full
        # bench records it too; this keeps the trend visible on CI
        # runners). Consensus batching per the 16-node A/B note in
        # node_testnet_events_per_sec.
        seps, _ = node_testnet_events_per_sec(
            engine="host", n_nodes=16, warm_s=8.0, window_s=12.0,
            interval=0.5, warm_gate_events=150, windows=1)
        payload["node16_events_per_s"] = round(seps, 1)
        payload["node_scaling_events_per_s"] = {
            "3": payload["node_events_per_s"], "16": round(seps, 1)}
    except Exception as exc:  # noqa: BLE001
        payload["node16_error"] = str(exc)
    try:
        # Durable-path leg: the same smoke over WAL-backed FileStores.
        # store_commit_share is the fraction of node phase wall spent
        # in sqlite COMMITs; the events/s delta against the in-mem leg
        # above is the full durable-path overhead (record in BENCH).
        # The scrape audit runs here too: the file leg must expose the
        # fsync-latency histogram on top of the core series.
        feps, fphases = node_testnet_events_per_sec(
            engine="host", n_nodes=3, warm_s=8.0, window_s=12.0,
            interval=0.0, warm_gate_events=200, windows=1,
            store="file", metrics_scrape=True)
        payload["node_file_events_per_s"] = round(feps, 1)
        payload["store_commit_share"] = fphases.get("store_commit_share")
        payload["file_commit_latency_p50_ms"] = fphases.get(
            "commit_latency_p50_ms")
        payload["file_commit_latency_p99_ms"] = fphases.get(
            "commit_latency_p99_ms")
    except Exception as exc:  # noqa: BLE001
        payload["file_store_error"] = str(exc)
    _emit(payload)
    return 0


def trace_overhead(reps=4, bar=0.05):
    """Interleaved A/B of the end-to-end tracing path (same protocol
    PR 5 used for the telemetry registry): `reps` back-to-back pairs
    of the 3-node host smoke, one leg with trace_sample=0 (stamping
    and flow emission must compile down to a falsy check) and one with
    tracing ON at a rate high enough to actually exercise the flow
    paths every window (0.05 — 50x the documented production default
    of 0.001, so the measurement bounds the real overhead from above).
    Interleaving absorbs machine drift; the medians must agree within
    `bar` (5%) or the exit code fails the CI job."""
    on_rate = 0.05
    off_rates, on_rates = [], []
    payload = {
        "metric": "trace_overhead_ab",
        "nodes": 3,
        "engine": "host",
        "trace_sample_on": on_rate,
        "reps": reps,
    }
    try:
        for rep in range(reps):
            for label, rate, acc in (("off", 0.0, off_rates),
                                     ("on", on_rate, on_rates)):
                eps, _ = node_testnet_events_per_sec(
                    engine="host", n_nodes=3, warm_s=6.0, window_s=8.0,
                    interval=0.0, warm_gate_events=150, windows=1,
                    trace_sample=rate)
                acc.append(eps)
                log(f"  rep {rep} {label}: {eps:,.1f} ev/s")
    except Exception as exc:  # noqa: BLE001
        payload["error"] = str(exc)
        _emit(payload)
        return 1
    off_rates.sort()
    on_rates.sort()
    med = lambda xs: (xs[len(xs) // 2] if len(xs) % 2  # noqa: E731
                      else (xs[len(xs) // 2 - 1] + xs[len(xs) // 2]) / 2)
    off_med, on_med = med(off_rates), med(on_rates)
    overhead = 1.0 - on_med / off_med if off_med > 0 else 0.0
    payload["off_events_per_s"] = [round(x, 1) for x in off_rates]
    payload["on_events_per_s"] = [round(x, 1) for x in on_rates]
    payload["off_median"] = round(off_med, 1)
    payload["on_median"] = round(on_med, 1)
    payload["overhead_pct"] = round(overhead * 100.0, 2)
    payload["bar_pct"] = bar * 100.0
    payload["within_bar"] = overhead <= bar
    _emit(payload)
    if overhead > bar:
        log(f"trace overhead {overhead:.1%} exceeds the {bar:.0%} bar")
        return 1
    return 0


def health_overhead(reps=4, bar=0.05):
    """Interleaved A/B of the consensus health plane (same protocol as
    trace_overhead): `reps` back-to-back pairs of the 3-node host
    smoke with the divergence sentinel + stall watchdog + progress
    gauges ON (the product default — chain hash per committed block,
    health sidecar on every gossip pull, watchdog thread polling) vs
    OFF. The medians must agree within `bar` (5%) or the exit code
    fails the CI job."""
    on_rates, off_rates = [], []
    payload = {
        "metric": "health_overhead_ab",
        "nodes": 3,
        "engine": "host",
        "reps": reps,
    }
    try:
        for rep in range(reps):
            for label, health, acc in (("off", False, off_rates),
                                       ("on", True, on_rates)):
                eps, _ = node_testnet_events_per_sec(
                    engine="host", n_nodes=3, warm_s=6.0, window_s=8.0,
                    interval=0.0, warm_gate_events=150, windows=1,
                    health=health)
                acc.append(eps)
                log(f"  rep {rep} health {label}: {eps:,.1f} ev/s")
    except Exception as exc:  # noqa: BLE001
        payload["error"] = str(exc)
        _emit(payload)
        return 1
    off_rates.sort()
    on_rates.sort()
    med = lambda xs: (xs[len(xs) // 2] if len(xs) % 2  # noqa: E731
                      else (xs[len(xs) // 2 - 1] + xs[len(xs) // 2]) / 2)
    off_med, on_med = med(off_rates), med(on_rates)
    overhead = 1.0 - on_med / off_med if off_med > 0 else 0.0
    payload["off_events_per_s"] = [round(x, 1) for x in off_rates]
    payload["on_events_per_s"] = [round(x, 1) for x in on_rates]
    payload["off_median"] = round(off_med, 1)
    payload["on_median"] = round(on_med, 1)
    payload["overhead_pct"] = round(overhead * 100.0, 2)
    payload["bar_pct"] = bar * 100.0
    payload["within_bar"] = overhead <= bar
    _emit(payload)
    if overhead > bar:
        log(f"health overhead {overhead:.1%} exceeds the {bar:.0%} bar")
        return 1
    return 0


def gossip_overhead(reps=4, bar=0.05):
    """Interleaved A/B of the gossip efficiency observatory (same
    protocol as trace/health_overhead): `reps` back-to-back pairs of
    the 3-node host smoke with the observatory ON (the product default
    — per-sync redundancy classification, the known-map snapshot, the
    creation-stamp sidecar on every self-event, the propagation
    histogram) vs OFF. The measurement plane that exists to find waste
    must not itself be waste: medians must agree within `bar` (5%) or
    the exit code fails the CI job."""
    on_rates, off_rates = [], []
    payload = {
        "metric": "gossip_overhead_ab",
        "nodes": 3,
        "engine": "host",
        "reps": reps,
    }
    try:
        for rep in range(reps):
            for label, obs, acc in (("off", False, off_rates),
                                    ("on", True, on_rates)):
                eps, _ = node_testnet_events_per_sec(
                    engine="host", n_nodes=3, warm_s=6.0, window_s=8.0,
                    interval=0.0, warm_gate_events=150, windows=1,
                    observatory=obs)
                acc.append(eps)
                log(f"  rep {rep} observatory {label}: {eps:,.1f} ev/s")
    except Exception as exc:  # noqa: BLE001
        payload["error"] = str(exc)
        _emit(payload)
        return 1
    off_rates.sort()
    on_rates.sort()
    med = lambda xs: (xs[len(xs) // 2] if len(xs) % 2  # noqa: E731
                      else (xs[len(xs) // 2 - 1] + xs[len(xs) // 2]) / 2)
    off_med, on_med = med(off_rates), med(on_rates)
    overhead = 1.0 - on_med / off_med if off_med > 0 else 0.0
    payload["off_events_per_s"] = [round(x, 1) for x in off_rates]
    payload["on_events_per_s"] = [round(x, 1) for x in on_rates]
    payload["off_median"] = round(off_med, 1)
    payload["on_median"] = round(on_med, 1)
    payload["overhead_pct"] = round(overhead * 100.0, 2)
    payload["bar_pct"] = bar * 100.0
    payload["within_bar"] = overhead <= bar
    _emit(payload)
    if overhead > bar:
        log(f"gossip overhead {overhead:.1%} exceeds the {bar:.0%} bar")
        return 1
    return 0


def capacity_overhead(reps=4, bar=0.05):
    """Interleaved A/B of the capacity observatory (same protocol as
    trace/health/gossip_overhead): `reps` back-to-back pairs of the
    3-node host smoke with the capacity plane ON (the product default
    — scrape-time sizers, the growth model, the cardinality audit) vs
    OFF, both legs scraped at 1 Hz so the on leg pays what a
    Prometheus-watched production node pays. The hot-path carries
    (cache hit/miss ints) are unconditional in both legs — the A/B
    bounds the scrape-time plane. Medians must agree within `bar` (5%)
    or the exit code fails the CI job."""
    on_rates, off_rates = [], []
    payload = {
        "metric": "capacity_overhead_ab",
        "nodes": 3,
        "engine": "host",
        "scrape_hz": 1.0,
        "reps": reps,
    }
    try:
        for rep in range(reps):
            for label, cap_on, acc in (("off", False, off_rates),
                                       ("on", True, on_rates)):
                eps, _ = node_testnet_events_per_sec(
                    engine="host", n_nodes=3, warm_s=6.0, window_s=8.0,
                    interval=0.0, warm_gate_events=150, windows=1,
                    capacity=cap_on, scrape_hz=1.0)
                acc.append(eps)
                log(f"  rep {rep} capacity {label}: {eps:,.1f} ev/s")
    except Exception as exc:  # noqa: BLE001
        payload["error"] = str(exc)
        _emit(payload)
        return 1
    off_rates.sort()
    on_rates.sort()
    med = lambda xs: (xs[len(xs) // 2] if len(xs) % 2  # noqa: E731
                      else (xs[len(xs) // 2 - 1] + xs[len(xs) // 2]) / 2)
    off_med, on_med = med(off_rates), med(on_rates)
    overhead = 1.0 - on_med / off_med if off_med > 0 else 0.0
    payload["off_events_per_s"] = [round(x, 1) for x in off_rates]
    payload["on_events_per_s"] = [round(x, 1) for x in on_rates]
    payload["off_median"] = round(off_med, 1)
    payload["on_median"] = round(on_med, 1)
    payload["overhead_pct"] = round(overhead * 100.0, 2)
    payload["bar_pct"] = bar * 100.0
    payload["within_bar"] = overhead <= bar
    _emit(payload)
    if overhead > bar:
        log(f"capacity overhead {overhead:.1%} exceeds the {bar:.0%} "
            f"bar")
        return 1
    return 0


# --------------------------------------------------------------------------
# Cluster-scaling gossip soak (docs/observability.md "Gossip efficiency"):
# the instrument the epidemic-broadcast rewrite will be accepted
# against. For each n it runs a live host testnet for a fixed wall,
# scrapes /metrics on an interval into a JSONL time-series ledger, and
# summarizes the per-n efficiency curves — per-node ev/s, redundancy
# ratio, duplicate share, propagation p50/p99, coverage time, and the
# known-map bookkeeping share the O(n) hypothesis blames.
# --------------------------------------------------------------------------


def profile_overhead(reps=4, bar=0.05):
    """Interleaved A/B of the in-process flame profiler (same protocol
    as trace/health/gossip_overhead): `reps` back-to-back pairs of the
    3-node host smoke, one leg with profile_hz=0 (the sampler thread
    must never be spawned — sampling-off is a strict no-op) and one at
    the documented production rate of 99 Hz, where the sampler walks
    sys._current_frames() under the GIL ~99 times a second. The
    medians must agree within `bar` (5%) or the exit code fails the CI
    job."""
    on_hz = 99.0
    off_rates, on_rates = [], []
    payload = {
        "metric": "profile_overhead_ab",
        "nodes": 3,
        "engine": "host",
        "profile_hz_on": on_hz,
        "reps": reps,
    }
    try:
        for rep in range(reps):
            for label, hz, acc in (("off", 0.0, off_rates),
                                   ("on", on_hz, on_rates)):
                eps, _ = node_testnet_events_per_sec(
                    engine="host", n_nodes=3, warm_s=6.0, window_s=8.0,
                    interval=0.0, warm_gate_events=150, windows=1,
                    profile_hz=hz)
                acc.append(eps)
                log(f"  rep {rep} profiler {label}: {eps:,.1f} ev/s")
    except Exception as exc:  # noqa: BLE001
        payload["error"] = str(exc)
        _emit(payload)
        return 1
    off_rates.sort()
    on_rates.sort()
    med = lambda xs: (xs[len(xs) // 2] if len(xs) % 2  # noqa: E731
                      else (xs[len(xs) // 2 - 1] + xs[len(xs) // 2]) / 2)
    off_med, on_med = med(off_rates), med(on_rates)
    overhead = 1.0 - on_med / off_med if off_med > 0 else 0.0
    payload["off_events_per_s"] = [round(x, 1) for x in off_rates]
    payload["on_events_per_s"] = [round(x, 1) for x in on_rates]
    payload["off_median"] = round(off_med, 1)
    payload["on_median"] = round(on_med, 1)
    payload["overhead_pct"] = round(overhead * 100.0, 2)
    payload["bar_pct"] = bar * 100.0
    payload["within_bar"] = overhead <= bar
    _emit(payload)
    if overhead > bar:
        log(f"profiler overhead {overhead:.1%} exceeds the {bar:.0%} bar")
        return 1
    return 0


def verify_bench(sizes=(1, 8, 64, 512), device_budget_s=150.0):
    """Crypto-plane microbenchmark (docs/ingest.md "Crypto plane"):
    per-backend serial vs batch vs device µs/event at batch sizes
    {1,8,64,512}, emitted as one JSON payload (metric `verify_bench`)
    whose headline keys bench_compare gates against the committed
    VERIFY_BENCH.json — a crypto regression fails CI like any other.

    Backends: the active host backend (`crypto.BACKEND`), the
    pure-python fallback when it is not already active, and the
    ops/p256.py device kernel when JAX is importable. Serial parses
    creator keys once outside the timer — the ingest path's
    `pub_key_from_bytes_cached` amortizes exactly that. The device leg
    respects `device_budget_s` and records any sizes it skipped (no
    silent caps; on a CPU-fallback runner the 512-lane kernel alone can
    cost minutes of XLA compile + run)."""
    import hashlib

    from babble_tpu import crypto
    from babble_tpu.crypto import _fallback as fb

    payload = {"metric": "verify_bench", "sizes": list(sizes),
               "backend_active": crypto.BACKEND}
    max_n = max(sizes)
    seeds = (1, 2, 3, 5)
    keys = [fb.key_from_seed(s) for s in seeds]
    pubs_b = [fb.pub_key_bytes(k) for k in keys]
    log(f"signing {max_n}-event corpus ({len(keys)} creators, "
        f"backend {crypto.BACKEND})")
    pubs, digests, sigs = [], [], []
    for i in range(max_n):
        d = hashlib.sha256(b"verify-bench-%d" % i).digest()
        pubs.append(pubs_b[i % len(keys)])
        digests.append(d)
        sigs.append(crypto.sign(keys[i % len(keys)], d))

    def _serial_host(name, verify_fn, key_of):
        cache = {p: key_of(p) for p in pubs_b}
        for s in sizes:
            reps = max(1, min(8, 256 // s))
            t0 = time.perf_counter()
            for _ in range(reps):
                for i in range(s):
                    verify_fn(cache[pubs[i]], digests[i], *sigs[i])
            us = (time.perf_counter() - t0) / (reps * s) * 1e6
            payload[f"verify_{name}_serial_us_{s}"] = round(us, 2)
            log(f"  {name} serial n={s}: {us:,.1f} us/ev")

    def _batch(name, batch_fn, budget_s=None):
        t_leg = time.monotonic()
        for s in sizes:
            if budget_s is not None and \
                    time.monotonic() - t_leg > budget_s:
                skipped = [x for x in sizes if x >= s]
                payload[f"verify_{name}_sizes_skipped"] = skipped
                log(f"  {name} batch: budget exhausted, "
                    f"skipping sizes {skipped}")
                break
            reps = max(1, min(8, 256 // s))
            batch_fn(pubs[:s], digests[:s], sigs[:s])  # warm (compile)
            t0 = time.perf_counter()
            for _ in range(reps):
                batch_fn(pubs[:s], digests[:s], sigs[:s])
            us = (time.perf_counter() - t0) / (reps * s) * 1e6
            payload[f"verify_{name}_batch_us_{s}"] = round(us, 2)
            log(f"  {name} batch n={s}: {us:,.1f} us/ev")

    _serial_host(crypto.BACKEND, crypto.verify,
                 crypto.pub_key_from_bytes)
    _batch(crypto.BACKEND, crypto.verify_batch)
    if crypto.BACKEND != "pure-python":
        _serial_host("pure-python", fb.verify, fb.pub_key_from_bytes)
        _batch("pure-python", fb.verify_batch)

    try:
        from babble_tpu.ops import p256
        device_ok = p256.available()
    except Exception:  # noqa: BLE001
        device_ok = False
    if device_ok:
        _batch("device-p256", p256.verify_batch,
               budget_s=device_budget_s)
    else:
        payload["device_skipped"] = "jax unavailable"
        log("  device-p256: skipped (jax unavailable)")

    _emit(payload)
    return 0


def _http_testnet(n_nodes, admission, quota_rate=0.0,
                  ingress_target=0.2, heartbeat=0.0015, interval=0.0):
    """A host testnet with a Service per node — the real HTTP intake
    path (docs/ingress.md). Returns (nodes, services); callers own
    run_async/shutdown/close."""
    from babble_tpu.service import Service

    nodes = build_host_testnet(
        n_nodes, engine="host", interval=interval, heartbeat=heartbeat,
        admission=admission, quota_rate=quota_rate,
        ingress_target=ingress_target)
    services = [Service("127.0.0.1:0", nd) for nd in nodes]
    for svc in services:
        svc.serve_async()
    return nodes, services


def _ingress_eps(admission, rate=400, batch=40, warm_s=6.0,
                 window_s=8.0):
    """Committed ev/s of a 3-node host testnet driven through the
    real HTTP batch-submit path at a fixed sub-capacity open-loop
    rate — the measured leg of the --ingress-overhead A/B. Admission
    ON routes tx intake through quota -> CoDel -> intake queue;
    OFF is the bare pre-ingress path (submit_ch direct)."""
    import threading
    import urllib.request

    from babble_tpu.service.ingress import encode_tx_batch

    nodes, services = _http_testnet(3, admission)
    stop = threading.Event()
    seq = [0]

    def bombard():
        i = 0
        period = batch / rate
        nxt = time.monotonic()
        while not stop.is_set():
            txs = []
            for _ in range(batch):
                txs.append(b"ingress tx %d" % seq[0])
                seq[0] += 1
            req = urllib.request.Request(
                f"http://{services[i % 3].addr}/submit/batch",
                data=encode_tx_batch(txs), method="POST")
            try:
                urllib.request.urlopen(req, timeout=5).read()
            except Exception:  # noqa: BLE001
                pass
            i += 1
            nxt += period
            delay = nxt - time.monotonic()
            if delay > 0:
                stop.wait(delay)
            else:
                # Fixed offered rate for the A/B: don't accumulate
                # scheduling debt into a burst.
                nxt = time.monotonic()

    committed = lambda: min(  # noqa: E731
        len(nd.core.get_consensus_events()) for nd in nodes)
    import sys as _sys
    old_switch = _sys.getswitchinterval()
    _sys.setswitchinterval(0.1)
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        threading.Thread(target=bombard, daemon=True).start()
        deadline = time.monotonic() + warm_s
        while time.monotonic() < deadline and committed() < 150:
            time.sleep(0.25)
        c0, t0 = committed(), time.monotonic()
        time.sleep(window_s)
        c1, t1 = committed(), time.monotonic()
        return (c1 - c0) / (t1 - t0)
    finally:
        _sys.setswitchinterval(old_switch)
        stop.set()
        for svc in services:
            svc.close()
        for nd in nodes:
            nd.shutdown()


def ingress_overhead(reps=4, bar=0.05):
    """Interleaved A/B of the ingress admission plane (same protocol
    as trace/health/gossip_overhead): `reps` back-to-back pairs of a
    3-node host testnet bombarded through the REAL HTTP batch-submit
    path at a fixed sub-capacity rate, one leg with the admission
    plane ON (per-client quota lookup, CoDel controller, bounded
    intake queue + coalesced pool inserts — the product default) and
    one with --no_admission (bare submit_ch intake). Under
    non-overload load the armor must be free: medians within `bar`
    (5%) or the exit code fails the CI job."""
    on_rates, off_rates = [], []
    payload = {
        "metric": "ingress_overhead_ab",
        "nodes": 3,
        "engine": "host",
        "reps": reps,
        "offered_tx_per_s": 400,
    }
    try:
        for rep in range(reps):
            for label, admission, acc in (("off", False, off_rates),
                                          ("on", True, on_rates)):
                eps = _ingress_eps(admission)
                acc.append(eps)
                log(f"  rep {rep} admission {label}: {eps:,.1f} ev/s")
    except Exception as exc:  # noqa: BLE001
        payload["error"] = str(exc)
        _emit(payload)
        return 1
    off_rates.sort()
    on_rates.sort()
    med = lambda xs: (xs[len(xs) // 2] if len(xs) % 2  # noqa: E731
                      else (xs[len(xs) // 2 - 1] + xs[len(xs) // 2]) / 2)
    off_med, on_med = med(off_rates), med(on_rates)
    overhead = 1.0 - on_med / off_med if off_med > 0 else 0.0
    payload["off_events_per_s"] = [round(x, 1) for x in off_rates]
    payload["on_events_per_s"] = [round(x, 1) for x in on_rates]
    payload["off_median"] = round(off_med, 1)
    payload["on_median"] = round(on_med, 1)
    payload["overhead_pct"] = round(overhead * 100.0, 2)
    payload["bar_pct"] = bar * 100.0
    payload["within_bar"] = overhead <= bar
    _emit(payload)
    if overhead > bar:
        log(f"ingress overhead {overhead:.1%} exceeds the {bar:.0%} bar")
        return 1
    return 0


def loadgen():
    """Load-generator mode (docs/ingress.md): drive >= 100k open
    client transactions (open-loop arrival — each client schedules
    sends by wall clock, never by response) from many quota'd clients
    through the real HTTP batch-submit path against a host testnet,
    then assert the overload contract straight from /metrics:

    - `babble_ingress_shed_total` > 0 and quota rejections > 0 (the
      offered rate is sized >= 2x the cluster's commit capacity, and
      a slice of clients is greedy past its bucket),
    - `babble_queue_dropped_total{queue="commit"}` == 0 — shedding
      happens at the FRONT door, the commit path never drops,
    - every ADMITTED transaction commits, byte-identically ordered
      across nodes,
    - the admitted-tx p99 commit latency (scraped, cross-node-merged
      histogram) meets the SLO.

    Emits one JSON payload (loadgen_* keys) gated by bench_compare
    against the committed LOADGEN_SMOKE.json. Env knobs:
    LOADGEN_NODES/TXS/RATE/CLIENTS/BATCH/SLO_MS/QUOTA."""
    import threading
    import urllib.request
    from urllib.error import HTTPError

    from babble_tpu.service.ingress import encode_tx_batch
    from babble_tpu.telemetry import promtext

    n_nodes = int(os.environ.get("LOADGEN_NODES", "3"))
    total_txs = int(os.environ.get("LOADGEN_TXS", "100000"))
    rate = float(os.environ.get("LOADGEN_RATE", "2500"))
    n_clients = int(os.environ.get("LOADGEN_CLIENTS", "24"))
    batch = int(os.environ.get("LOADGEN_BATCH", "100"))
    slo_ms = float(os.environ.get("LOADGEN_SLO_MS", "10000"))
    fair = rate / n_clients
    # Per-client quota at 2x fair share: in-contract clients never see
    # the bucket; every 6th client offers 4x fair share and MUST get
    # quota-rejected — the quota plane exercised, not just configured.
    quota_rate = float(os.environ.get("LOADGEN_QUOTA", str(2.0 * fair)))
    payload = {
        "metric": "loadgen",
        "nodes": n_nodes,
        "engine": "host",
        "loadgen_offered_target": total_txs,
        "loadgen_rate_tx_per_s": rate,
        "loadgen_clients": n_clients,
        "loadgen_quota_tx_per_s": round(quota_rate, 1),
        "loadgen_slo_ms": slo_ms,
    }
    try:
        calib_eps, _, _ = host_engine_events_per_sec(64, 5000)
        payload["host_events_per_s"] = round(calib_eps, 1)
    except Exception as exc:  # noqa: BLE001
        payload["calibration_error"] = str(exc)

    nodes, services = _http_testnet(
        n_nodes, admission=True, quota_rate=quota_rate, interval=0.03)
    lock = threading.Lock()
    counts = {"offered": 0, "accepted": 0, "shed": 0,
              "quota_rejected": 0, "http_429": 0, "errors": 0}
    admitted: set = set()
    stop = threading.Event()

    def client(idx):
        greedy = idx % 6 == 0
        my_rate = fair * (4.0 if greedy else 1.0)
        period = batch / my_rate
        svc = services[idx % n_nodes]
        url = f"http://{svc.addr}/submit/batch"
        nxt = time.monotonic()
        i = 0
        while not stop.is_set():
            with lock:
                if counts["offered"] >= total_txs:
                    return
                base = counts["offered"]
                counts["offered"] += batch
            txs = [b"lg %d %d %d" % (idx, i, base + k)
                   for k in range(batch)]
            i += 1
            req = urllib.request.Request(
                url, data=encode_tx_batch(txs), method="POST",
                headers={"X-Babble-Client": f"lg-{idx}"})
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    doc = json.loads(r.read())
            except HTTPError as e:
                # 429 = the whole batch was rejected; the body still
                # carries the shed/quota split.
                try:
                    doc = json.loads(e.read())
                except Exception:  # noqa: BLE001
                    doc = {}
                with lock:
                    counts["http_429"] += 1
                    counts["shed"] += int(doc.get("shed", 0))
                    counts["quota_rejected"] += int(
                        doc.get("quota_rejected", batch))
                doc = None
            except Exception:  # noqa: BLE001
                with lock:
                    counts["errors"] += 1
                doc = None
            if doc is not None:
                with lock:
                    counts["accepted"] += int(doc.get("submitted", 0))
                    counts["shed"] += int(doc.get("shed", 0))
                    counts["quota_rejected"] += int(
                        doc.get("quota_rejected", 0))
                    for tx, st in zip(txs, doc.get("statuses", [])):
                        if st == "accepted":
                            admitted.add(tx)
            # Open-loop arrival: the next send is scheduled by wall
            # clock from the PREVIOUS schedule point, not from when
            # the response came back.
            nxt += period
            delay = nxt - time.monotonic()
            if delay > 0:
                stop.wait(delay)

    committed_txs = lambda nd: nd.core.get_consensus_transactions()  # noqa: E731
    import sys as _sys
    old_switch = _sys.getswitchinterval()
    _sys.setswitchinterval(0.1)
    t0 = time.monotonic()
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True)
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        # Progress log while the offered load drains out.
        while any(t.is_alive() for t in threads):
            time.sleep(2.0)
            with lock:
                snap = dict(counts)
            log(f"  offered {snap['offered']:,} accepted "
                f"{snap['accepted']:,} shed {snap['shed']:,} quota "
                f"{snap['quota_rejected']:,}")
        offered_wall = time.monotonic() - t0
        # Drain: every admitted tx must land in every node's committed
        # stream (the front door shed instead of the commit path
        # dropping — nothing admitted may be lost).
        drain_deadline = time.monotonic() + max(
            120.0, 30.0 * n_nodes)
        pending = len(nodes)
        while time.monotonic() < drain_deadline:
            pending = sum(
                1 for nd in nodes
                if not admitted.issubset(set(committed_txs(nd))))
            if pending == 0:
                break
            time.sleep(1.0)
        wall = time.monotonic() - t0
        with lock:
            snap = dict(counts)
        payload.update({
            "loadgen_offered": snap["offered"],
            "loadgen_admitted": snap["accepted"],
            "loadgen_shed": snap["shed"],
            "loadgen_quota_rejected": snap["quota_rejected"],
            "loadgen_http_429": snap["http_429"],
            "loadgen_errors": snap["errors"],
            "loadgen_offered_wall_s": round(offered_wall, 1),
            "loadgen_wall_s": round(wall, 1),
            "loadgen_admitted_per_s": round(
                snap["accepted"] / offered_wall, 1),
            "loadgen_shed_share": round(
                snap["shed"] / max(snap["offered"], 1), 3),
        })
        # The /metrics-side contract: scrape every node's service,
        # merge the commit-latency histograms, sum the shed/drop
        # counters — the same bytes a Prometheus server would see.
        lat_snap = None
        shed_total = 0.0
        quota_total = 0.0
        commit_drops = 0.0
        for svc in services:
            with urllib.request.urlopen(
                    f"http://{svc.addr}/metrics", timeout=10) as r:
                samples, _ = promtext.parse(r.read().decode())
            h = promtext.histogram_snapshot(
                samples, "babble_commit_latency_seconds")
            lat_snap = h if lat_snap is None else lat_snap.merge(h)
            shed_total += sum(
                v for _lb, v in samples.get(
                    "babble_ingress_shed_total", []))
            quota_total += sum(
                v for _lb, v in samples.get(
                    "babble_ingress_quota_rejected_total", []))
            commit_drops += sum(
                v for lb, v in samples.get(
                    "babble_queue_dropped_total", [])
                if lb.get("queue") == "commit")
        p99_ms = round(lat_snap.quantile(0.99) * 1000.0, 1)
        p50_ms = round(lat_snap.quantile(0.5) * 1000.0, 1)
        payload["loadgen_commit_latency_p99_ms"] = p99_ms
        payload["loadgen_commit_latency_p50_ms"] = p50_ms
        payload["loadgen_scraped_shed_total"] = int(shed_total)
        payload["loadgen_scraped_quota_rejected"] = int(quota_total)
        payload["loadgen_commit_drops"] = int(commit_drops)
        # Byte-identical order across nodes over the common prefix.
        streams = [committed_txs(nd) for nd in nodes]
        prefix = min(len(s) for s in streams)
        order_ok = all(s[:prefix] == streams[0][:prefix]
                       for s in streams)
        payload["loadgen_committed_txs"] = prefix
        failures = []
        if snap["offered"] < total_txs:
            failures.append(
                f"offered {snap['offered']} < target {total_txs}")
        if shed_total + quota_total <= 0:
            failures.append("no sheds or quota rejections under a "
                            ">=2x-capacity firehose")
        if quota_total <= 0:
            failures.append("greedy clients never hit their quota")
        if commit_drops > 0:
            failures.append(f"commit_ch dropped {int(commit_drops)}")
        if pending > 0:
            failures.append(
                f"{pending} node(s) missing admitted txs after drain")
        if not order_ok:
            failures.append("committed tx order diverged across nodes")
        if p99_ms > slo_ms:
            failures.append(
                f"admitted p99 {p99_ms}ms exceeds SLO {slo_ms}ms")
        payload["loadgen_pass"] = not failures
        if failures:
            payload["error"] = "; ".join(failures)
        _emit(payload)
        return 1 if failures else 0
    except Exception as exc:  # noqa: BLE001
        payload["error"] = str(exc)
        _emit(payload)
        return 1
    finally:
        _sys.setswitchinterval(old_switch)
        stop.set()
        for svc in services:
            svc.close()
        for nd in nodes:
            nd.shutdown()


def _soak_coverage_probe(nodes, timeout=15.0):
    """Coverage time: wall seconds for node 0's NEXT self-event to
    become known to every node (the known maps are read through the
    raw store path so the probe does not inflate the `known` phase it
    is measuring). None when the net is too stalled to measure."""
    n0 = nodes[0]
    pid0 = n0.core.participants[n0.core.hex_id()]
    target = n0.core.seq + 1
    deadline = time.monotonic() + timeout
    while n0.core.seq < target:
        if time.monotonic() > deadline:
            return None
        time.sleep(0.001)
    t0 = time.monotonic()
    remaining = set(range(1, len(nodes)))
    while remaining:
        if time.monotonic() > deadline:
            return None
        for i in list(remaining):
            if nodes[i].core.hg.known().get(pid0, -1) >= target:
                remaining.discard(i)
        time.sleep(0.002)
    return time.monotonic() - t0


def gossip_soak_leg(n, wall_s, scrape_s, ts_file, probes=5):
    """One soak leg: n in-process host nodes under continuous load for
    `wall_s` of measurement, /metrics scraped over real HTTP every
    `scrape_s` (parse-validated) with per-node counter rows appended
    to the JSONL ledger `ts_file`. Returns the leg summary dict."""
    import threading
    import urllib.request

    from babble_tpu.service import Service
    from babble_tpu.telemetry import promtext

    # n >= 16 batches several syncs per consensus pass, matching the
    # node16 smoke leg (amortizes the undecided-round rescan).
    interval = 0.5 if n >= 16 else 0.0
    nodes = build_host_testnet(n, engine="host", interval=interval,
                               heartbeat=0.0015)
    svc = Service("127.0.0.1:0", nodes[0])
    svc.serve_async()
    stop = threading.Event()
    coverage: list = []

    def bombard():
        i = 0
        while not stop.is_set():
            try:
                nodes[i % n].submit_tx(f"soak tx {i}".encode())
            except Exception:  # noqa: BLE001
                pass
            i += 1
            time.sleep(0.002)

    def probe_loop():
        gap = max(wall_s / (probes + 1), 0.5)
        while not stop.is_set() and len(coverage) < probes:
            c = _soak_coverage_probe(nodes)
            if c is not None:
                coverage.append(c)
            if stop.wait(gap):
                return

    committed = lambda: min(  # noqa: E731
        len(nd.core.get_consensus_events()) for nd in nodes)
    agg_snap = lambda nd: {  # noqa: E731
        k: c.value for k, c in nd._m_gossip_agg.items()}

    def sat_agg():
        # Queue saturation across ALL nodes, folded by queue family
        # (per-peer plumtree_push:<addr> entries collapse into one
        # row): wait p99 takes the max (the bottleneck criterion),
        # drops sum, depth/capacity report the worst occupant.
        out: dict = {}
        for nd in nodes:
            for name, s in nd.saturation_stats().items():
                fam = name.split(":", 1)[0]
                row = out.setdefault(fam, {
                    "depth": 0, "capacity": 0, "wait_p99_ms": 0.0,
                    "dropped": 0, "waits": 0})
                row["depth"] = max(row["depth"], s.get("depth", 0))
                row["capacity"] = max(row["capacity"],
                                      s.get("capacity", 0))
                if s.get("wait_p99_ms") is not None:
                    row["wait_p99_ms"] = max(row["wait_p99_ms"],
                                             s["wait_p99_ms"])
                row["dropped"] += int(s.get("dropped", 0))
                row["waits"] += int(s.get("waits", 0))
        return out

    def cpu_from_samples(samples):
        # Thread CPU folded by role (babble-worker-3 -> babble-worker,
        # Thread-42 (handle) -> Thread (handle)) so the curve stays
        # n-independent; the utilization gauge rides along.
        import re as _re

        by_role: dict = {}
        for lb, v in samples.get("babble_thread_cpu_seconds_total", []):
            role = _re.sub(r"-\d+", "", lb.get("thread", "?"))
            by_role[role] = round(by_role.get(role, 0.0) + v, 3)
        util = samples.get("babble_cpu_utilization_cores", [])
        return by_role, (round(util[0][1], 3) if util else None)

    def plumtree_snap():
        out = {"grafts": 0, "prunes": 0, "shed": 0}
        for nd in nodes:
            pt = nd.plumtree
            if pt is None:
                continue
            out["grafts"] += int(pt._m_graft["tx"].value)
            out["prunes"] += int(pt._m_prune["tx"].value)
            out["shed"] += int(pt._m_shed.value)
        return out

    def leg_snap():
        # Cluster totals per ingest leg (eager / lazy_pull / graft /
        # pull / push_in): the acceptance split for the tree rewrite.
        out: dict = {}
        for nd in nodes:
            for (_p, leg), ch in list(nd._gossip_children.items()):
                row = out.setdefault(leg, {"new": 0, "duplicate": 0})
                row["new"] += int(ch["new"].value)
                row["duplicate"] += int(ch["duplicate"].value)
        return out

    import sys as _sys
    old_switch = _sys.getswitchinterval()
    _sys.setswitchinterval(0.1)
    rows_written = 0
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        threading.Thread(target=bombard, daemon=True).start()
        # Warmup: first commits prove the net is live before the
        # measurement window opens. The cap scales with n — at n=32
        # the first rounds take ~60 s to decide (round cadence is the
        # cluster's end-to-end propagation time, not CPU), and opening
        # the window during that ramp measures the ramp, not the
        # steady state.
        deadline = time.monotonic() + max(6.0, wall_s / 3.0, 3.0 * n)
        while time.monotonic() < deadline and committed() < 100:
            time.sleep(0.25)
        threading.Thread(target=probe_loop, daemon=True).start()

        c0, t0 = committed(), time.monotonic()
        g0 = [agg_snap(nd) for nd in nodes]
        p0 = [nd.core._m_propagation.snapshot() for nd in nodes]
        pt0 = plumtree_snap()
        legs0 = leg_snap()
        phase0: dict = {}
        for nd in nodes:
            for ph, ent in list(nd.core.phase_ns.items()):
                phase0[ph] = phase0.get(ph, 0) + ent[1]
        with open(ts_file, "a") as ts:
            while time.monotonic() - t0 < wall_s:
                time.sleep(scrape_s)
                now = round(time.monotonic() - t0, 2)
                # Real HTTP scrape of node 0 — parse-validated, the
                # same bytes a Prometheus server would ingest.
                with urllib.request.urlopen(
                        f"http://{svc.addr}/metrics", timeout=10) as r:
                    samples, _ = promtext.parse(r.read().decode())
                scraped = {
                    kind: sum(
                        v for lb, v in samples.get(
                            f"babble_gossip_{kind}_events_total", [])
                        if lb.get("node") == "0" and "peer" not in lb)
                    for kind in ("offered", "new", "duplicate")}
                ts.write(json.dumps(
                    {"t": now, "n": n, "node": "scrape0"} | scraped)
                    + "\n")
                rows_written += 1
                # Saturation curves (docs/observability.md
                # "Saturation"): per-family queue depth/wait and the
                # role-folded thread CPU totals, one row each per
                # scrape tick.
                ts.write(json.dumps({
                    "t": now, "n": n, "node": "sat",
                    "queues": {
                        fam: {"depth": r["depth"],
                              "wait_p99_ms": r["wait_p99_ms"],
                              "dropped": r["dropped"]}
                        for fam, r in sat_agg().items()},
                }) + "\n")
                by_role, util = cpu_from_samples(samples)
                ts.write(json.dumps({
                    "t": now, "n": n, "node": "cpu",
                    "thread_cpu_s": by_role,
                    "utilization_cores": util,
                }) + "\n")
                rows_written += 2
                for i, nd in enumerate(nodes):
                    snap = agg_snap(nd)
                    ts.write(json.dumps({
                        "t": now, "n": n, "node": i,
                        "consensus_events":
                            len(nd.core.get_consensus_events()),
                        **{k: int(v) for k, v in snap.items()},
                    }) + "\n")
                    rows_written += 1
        wall = time.monotonic() - t0
        c1 = committed()
        g1 = [agg_snap(nd) for nd in nodes]
        pt1 = plumtree_snap()
        legs1 = leg_snap()
        prop = None
        for nd, before in zip(nodes, p0):
            delta = nd.core._m_propagation.snapshot() - before
            prop = delta if prop is None else prop.merge(delta)
        phase1: dict = {}
        for nd in nodes:
            for ph, ent in list(nd.core.phase_ns.items()):
                phase1[ph] = phase1.get(ph, 0) + ent[1]
        # End-of-leg saturation summary, harvested while the nodes are
        # still alive (saturation_stats reads live queue instruments).
        sat1 = sat_agg()
        try:
            with urllib.request.urlopen(
                    f"http://{svc.addr}/metrics", timeout=10) as r:
                fsamples, _ = promtext.parse(r.read().decode())
            cpu_roles, cpu_util = cpu_from_samples(fsamples)
        except Exception:  # noqa: BLE001
            cpu_roles, cpu_util = {}, None
    finally:
        _sys.setswitchinterval(old_switch)
        stop.set()
        for nd in nodes:
            nd.shutdown()
        svc.close()

    tot = {k: sum(b[k] - a[k] for a, b in zip(g0, g1))
           for k in g0[0]} if g0 else {}
    plumtree_counters = ({k: pt1[k] - pt0[k] for k in pt1}
                         if any(nd.plumtree is not None for nd in nodes)
                         else {})
    leg_totals = {}
    for lg, row1 in legs1.items():
        row0 = legs0.get(lg, {"new": 0, "duplicate": 0})
        lnew = row1["new"] - row0["new"]
        ldup = row1["duplicate"] - row0["duplicate"]
        if lnew or ldup:
            leg_totals[lg] = {
                "new": lnew, "duplicate": ldup,
                "redundancy_ratio": (round(ldup / lnew, 3)
                                     if lnew else None)}
    offered = tot.get("offered", 0)
    new = tot.get("new", 0)
    dup = tot.get("duplicate", 0)
    # Pacing/bookkeeping attribution over the window (same share
    # denominators as node_testnet_events_per_sec).
    dphase = {ph: phase1.get(ph, 0) - phase0.get(ph, 0) for ph in phase1}
    ingest = ("from_wire", "wire_unpack", "verify", "insert")
    # verify_<backend> is the same interval as verify under a
    # backend-keyed name — excluded so the verify wall isn't counted
    # twice in the pacing denominator.
    top = {ph: v for ph, v in dphase.items()
           if not ph.startswith("engine_") and ph not in ingest
           and not ph.startswith("verify_")
           and ph != "store_commit" and v > 0}
    top_sum = sum(top.values())
    leg = {
        "n": n,
        "wall_s": round(wall, 1),
        # Core budget + runtime stamped on EVERY ledger entry: the
        # machine-readable honesty note. bench_compare auto-skips
        # multicore-only gates when either side ran on < 2 cores.
        "cpus_effective": _cpus_effective(),
        "runtime": _runtime_arg(),
        "events_per_s": round((c1 - c0) / wall, 1),
        "offered_events": int(offered),
        "new_events": int(new),
        "duplicate_events": int(dup),
        "stale_events": int(tot.get("stale", 0)),
        "payload_bytes": int(tot.get("bytes", 0)),
        # duplicates per NEW event: the gossip amplification waste
        # (0 = perfect); duplicate_share is the same waste as a
        # fraction of everything offered (bounded [0, 1]).
        "redundancy_ratio": round(dup / new, 3) if new else None,
        "duplicate_share": round(dup / offered, 3) if offered else None,
        "bytes_per_new_event": round(tot.get("bytes", 0) / new, 1)
        if new else None,
        "coverage_ms": (round(
            1e3 * sorted(coverage)[len(coverage) // 2], 1)
            if coverage else None),
        "coverage_probes": len(coverage),
        "timeseries_rows": rows_written,
    }
    if prop is not None and prop.count:
        leg["propagation_p50_ms"] = round(prop.quantile(0.5) * 1e3, 2)
        leg["propagation_p99_ms"] = round(prop.quantile(0.99) * 1e3, 2)
        leg["propagation_samples"] = prop.count
    # Epidemic broadcast tree churn (docs/gossip.md): graft/prune
    # totals over the window — a settled tree shows ~0 churn per
    # second, repair storms show up immediately.
    if plumtree_counters:
        for k, v in plumtree_counters.items():
            leg[k] = v
        leg["grafts_per_s"] = round(
            plumtree_counters.get("grafts", 0) / wall, 2)
        leg["prunes_per_s"] = round(
            plumtree_counters.get("prunes", 0) / wall, 2)
    # Per-leg redundancy split (eager plane vs anti-entropy backstop):
    # the acceptance view — eager should carry nearly all new events
    # at low duplicate cost once the tree settles.
    if leg_totals:
        leg["legs"] = leg_totals
    # Saturation summary (USE-method: which queue is the bottleneck,
    # where did the CPU-seconds go). wait p99 is the bottleneck
    # criterion — the queue where enqueued work waited longest.
    if sat1:
        leg["queues"] = sat1
        bq = max(sat1.items(), key=lambda kv: kv[1]["wait_p99_ms"])
        leg["bottleneck_queue"] = bq[0]
        leg["queue_wait_p99_ms"] = round(bq[1]["wait_p99_ms"], 2)
    if cpu_roles:
        leg["thread_cpu_s"] = cpu_roles
    if cpu_util is not None:
        leg["cpu_utilization_cores"] = cpu_util
    if top_sum:
        leg["phase_share"] = {ph: round(v / top_sum, 3)
                              for ph, v in sorted(top.items())}
        # The suspected O(n) term: known-map walks + diff merges as a
        # share of the top-level phase wall.
        leg["bookkeeping_share"] = round(
            (dphase.get("known", 0) + dphase.get("diff", 0)) / top_sum,
            3)
    if dphase.get("sync"):
        # Inside the sync wall (docs/ingest.md): materialize / verify /
        # insert split — when `sync` dominates the leg, this names the
        # stage that grew with n.
        leg["ingest_phase_share"] = {
            ph: round(dphase.get(ph, 0) / dphase["sync"], 3)
            for ph in ingest if dphase.get(ph)}
    return leg


def gossip_soak():
    """`bench.py --soak`: the cluster-scaling soak ledger. Legs and
    wall come from SOAK_NS / SOAK_WALL_S / SOAK_SCRAPE_S (defaults
    n∈{3,8,16,32}, 45 s, 2 s) so CI can run a {3,8} smoke against the
    same committed SOAK_SMOKE.json baseline (bench_compare gates the
    keys both payloads carry). Emits one JSON payload; the raw
    time-series JSONL lands in SOAK_OUT_DIR."""
    import tempfile

    ns = [int(x) for x in os.environ.get(
        "SOAK_NS", "3,8,16,32").split(",") if x.strip()]
    wall_s = float(os.environ.get("SOAK_WALL_S", "45"))
    scrape_s = float(os.environ.get("SOAK_SCRAPE_S", "2.0"))
    out_dir = os.environ.get("SOAK_OUT_DIR") or tempfile.mkdtemp(
        prefix="babble-soak-")
    os.makedirs(out_dir, exist_ok=True)
    ts_file = os.path.join(out_dir, "soak_timeseries.jsonl")
    # Multicore leg (`--cpus K` / SOAK_CPUS): pin the whole testnet
    # process to K cores so thread CPU attribution and the queue
    # curves are measured under a known core budget. Pinning is
    # best-effort — the host may expose fewer cores than asked
    # (cpus_effective records what the run actually had, and the
    # ledger keeps both so a 1-core container's numbers are never
    # mistaken for a 2-core result).
    cpus_req = None
    if "--cpus" in sys.argv:
        try:
            cpus_req = int(sys.argv[sys.argv.index("--cpus") + 1])
        except (IndexError, ValueError):
            log("--cpus needs an integer argument")
            return 1
    elif os.environ.get("SOAK_CPUS"):
        cpus_req = int(os.environ["SOAK_CPUS"])
    payload = {
        "metric": "gossip_soak_multicore" if cpus_req else "gossip_soak",
        "unit": "events/s",
        "engine": "host",
        "runtime": _runtime_arg(),
        "wall_s_per_leg": wall_s,
        "timeseries_jsonl": ts_file,
        "legs": {},
    }
    if cpus_req:
        payload["cpus_requested"] = cpus_req
        if hasattr(os, "sched_setaffinity"):
            avail = sorted(os.sched_getaffinity(0))
            os.sched_setaffinity(0, set(avail[:cpus_req]))
    # Recorded UNCONDITIONALLY (post-pinning), not just on --cpus
    # runs: every soak ledger carries its real core budget, so
    # bench_compare can machine-skip multicore-only gates instead of
    # relying on a hand-written honest note.
    payload["cpus_effective"] = _cpus_effective()
    if cpus_req:
        log(f"soak multicore: requested {cpus_req} cpus, "
            f"effective {payload['cpus_effective']}")
    # 1->2 core scaling factor (ROADMAP multicore gate): when
    # SOAK_BASELINE_JSON names a prior soak payload (the 1-core
    # reference leg), each leg's throughput is expressed as a factor
    # over the baseline's same-n leg — the `soak{n}_scaling_x`
    # headline bench_compare gates as a raw factor (no machine
    # normalization: both runs happened on THIS machine).
    base_eps: dict = {}
    bp = os.environ.get("SOAK_BASELINE_JSON")
    if bp and os.path.exists(bp):
        try:
            with open(bp) as f:
                bj = json.load(f)
            base_eps = {k: v for k, v in bj.items()
                        if k.endswith("_events_per_s")
                        and isinstance(v, (int, float))}
            payload["scaling_baseline"] = bp
        except Exception as exc:  # noqa: BLE001
            log(f"scaling baseline unreadable: {exc}")
    try:
        # The shared machine-speed yardstick (see bench_compare.py).
        calib_eps, _, _ = host_engine_events_per_sec(64, 5000)
        payload["host_events_per_s"] = round(calib_eps, 1)
        payload["host_events"] = 5000
    except Exception as exc:  # noqa: BLE001
        payload["calibration_error"] = str(exc)
    failures = 0
    for n in ns:
        log(f"soak leg n={n}: {wall_s:.0f}s wall, "
            f"scrape every {scrape_s:.1f}s")
        try:
            leg = gossip_soak_leg(n, wall_s, scrape_s, ts_file)
        except Exception as exc:  # noqa: BLE001
            payload[f"soak{n}_error"] = str(exc)
            failures += 1
            _emit(payload)
            continue
        payload["legs"][str(n)] = leg
        payload[f"soak{n}_events_per_s"] = leg["events_per_s"]
        for k in ("redundancy_ratio", "duplicate_share",
                  "bytes_per_new_event", "propagation_p50_ms",
                  "propagation_p99_ms", "coverage_ms",
                  "bookkeeping_share", "grafts_per_s", "prunes_per_s",
                  "queue_wait_p99_ms", "cpu_utilization_cores"):
            if leg.get(k) is not None:
                payload[f"soak{n}_{k}"] = leg[k]
        # Per-leg redundancy (docs/gossip.md): the eager plane is the
        # headline — a settled tree delivers ~once per event there.
        eager = (leg.get("legs") or {}).get("eager") or {}
        if eager.get("redundancy_ratio") is not None:
            payload[f"soak{n}_eager_redundancy_ratio"] = \
                eager["redundancy_ratio"]
        # Crypto-plane multicore gate (ROADMAP "verify share < 0.3"):
        # verify's share of the sync wall, a multicore-only headline —
        # bench_compare skips it when cpus_effective < 2.
        ing = leg.get("ingest_phase_share") or {}
        if ing.get("verify") is not None:
            payload[f"soak{n}_verify_share"] = ing["verify"]
        base = base_eps.get(f"soak{n}_events_per_s")
        if base:
            payload[f"soak{n}_scaling_x"] = round(
                leg["events_per_s"] / base, 2)
        log(f"  n={n}: {leg['events_per_s']:,.1f} ev/s, redundancy "
            f"{leg['redundancy_ratio']}, dup share "
            f"{leg['duplicate_share']}, propagation p99 "
            f"{leg.get('propagation_p99_ms')} ms, bookkeeping share "
            f"{leg.get('bookkeeping_share')}")
        _emit(payload)
    payload["node_scaling_events_per_s"] = {
        str(n): payload[f"soak{n}_events_per_s"]
        for n in ns if f"soak{n}_events_per_s" in payload}
    _emit(payload)
    return 1 if failures else 0


# --------------------------------------------------------------------------
# Retention soak (docs/observability.md "Capacity"): the state-growth
# ledger the checkpoint/compaction work will be accepted against. Each
# leg runs a WAL-backed host testnet under fixed load, samples the
# capacity families over real HTTP on an interval, and fits
# bytes-per-committed-event slopes for total retained state, the
# process RSS, and the WAL — plus the named top-growing component from
# /debug/capacity. bench_compare gates the slopes against the
# committed RETENTION_SMOKE.json.
# --------------------------------------------------------------------------


def retention_leg(n, wall_s, scrape_s, ts_file):
    """One retention leg: n host nodes over WAL-backed FileStores
    under continuous load for `wall_s`, capacity families scraped over
    real HTTP every `scrape_s` into the JSONL ledger `ts_file`.
    Returns the leg summary with the fitted growth slopes."""
    import threading
    import urllib.request

    from babble_tpu.service import Service
    from babble_tpu.telemetry import promtext
    from babble_tpu.telemetry.capacity import GrowthTracker

    interval = 0.5 if n >= 16 else 0.0
    nodes = build_host_testnet(n, engine="host", interval=interval,
                               heartbeat=0.0015, store="file")
    svc = Service("127.0.0.1:0", nodes[0])
    svc.serve_async()
    stop = threading.Event()

    def bombard():
        i = 0
        while not stop.is_set():
            try:
                nodes[i % n].submit_tx(f"retention tx {i}".encode())
            except Exception:  # noqa: BLE001
                pass
            i += 1
            time.sleep(0.002)

    committed = lambda: min(  # noqa: E731
        len(nd.core.get_consensus_events()) for nd in nodes)

    # The slope fitter the node itself uses — one model, two callers.
    growth = GrowthTracker(window=4096)
    samples_taken = 0
    import sys as _sys
    old_switch = _sys.getswitchinterval()
    _sys.setswitchinterval(0.1)
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        threading.Thread(target=bombard, daemon=True).start()
        deadline = time.monotonic() + max(6.0, wall_s / 3.0, 3.0 * n)
        while time.monotonic() < deadline and committed() < 100:
            time.sleep(0.25)
        c0, t0 = committed(), time.monotonic()
        with open(ts_file, "a") as ts:
            while time.monotonic() - t0 < wall_s:
                time.sleep(scrape_s)
                now = round(time.monotonic() - t0, 2)
                ev = committed()
                # Real HTTP scrape — the same bytes Prometheus would
                # ingest, parse-validated.
                with urllib.request.urlopen(
                        f"http://{svc.addr}/metrics", timeout=10) as r:
                    samples, _ = promtext.parse(r.read().decode())
                node0 = lambda fam: {  # noqa: E731
                    lb.get("component") or lb.get("file") or "": v
                    for lb, v in samples.get(fam, [])
                    if lb.get("node", "0") == "0"}
                mem = node0("babble_mem_bytes")
                files = node0("babble_store_bytes")
                rss_rows = samples.get("babble_process_rss_bytes", [])
                rss = rss_rows[0][1] if rss_rows else 0
                mem_total = sum(mem.values())
                # x = committed events: the slopes read directly as
                # bytes per committed event.
                growth.observe("mem_total", ev, mem_total)
                growth.observe("rss", ev, rss)
                if "wal" in files:
                    growth.observe("wal", ev, files["wal"])
                if "journal" in files:
                    growth.observe("journal", ev, files["journal"])
                for comp, b in mem.items():
                    growth.observe(f"mem:{comp}", ev, b)
                ts.write(json.dumps({
                    "t": now, "n": n, "node": "capacity",
                    "committed_events": ev,
                    "mem_total_bytes": int(mem_total),
                    "rss_bytes": int(rss),
                    "files": {k: int(v) for k, v in files.items()},
                    "components": {k: int(v) for k, v in mem.items()},
                }) + "\n")
                samples_taken += 1
        wall = time.monotonic() - t0
        c1 = committed()
        # Final /debug/capacity read while the net is live: the ranked
        # top-growers table names the verdict component.
        try:
            with urllib.request.urlopen(
                    f"http://{svc.addr}/debug/capacity", timeout=10) \
                    as r:
                cap_dbg = json.loads(r.read())
        except Exception:  # noqa: BLE001
            cap_dbg = {}
    finally:
        _sys.setswitchinterval(old_switch)
        stop.set()
        for nd in nodes:
            nd.shutdown()
        svc.close()

    sl = lambda s: growth.slope(s)  # noqa: E731
    rnd = lambda v: None if v is None else round(v, 2)  # noqa: E731
    # Top grower by fitted slope across the per-component series (the
    # node's own /debug/capacity table rides along as a cross-check).
    comp_slopes = {s[len("mem:"):]: v for s, v in growth.slopes().items()
                   if s.startswith("mem:") and v is not None}
    top = max(comp_slopes.items(), key=lambda kv: kv[1]) \
        if comp_slopes else (None, None)
    leg = {
        "n": n,
        "wall_s": round(wall, 1),
        "cpus_effective": _cpus_effective(),
        "runtime": _runtime_arg(),
        "events_per_s": round((c1 - c0) / wall, 1),
        "committed_events": c1,
        "samples": samples_taken,
        "bytes_per_event": rnd(sl("mem_total")),
        "rss_slope_bytes_per_event": rnd(sl("rss")),
        "wal_slope_bytes_per_event": rnd(sl("wal")),
        "journal_slope_bytes_per_event": rnd(sl("journal")),
        "mem_total_bytes": (int(growth.last("mem_total"))
                           if growth.last("mem_total") else 0),
        "rss_bytes": (int(growth.last("rss"))
                      if growth.last("rss") else 0),
        "top_grower": top[0],
        "top_grower_bytes_per_event": rnd(top[1]),
        "component_slopes": {k: round(v, 2)
                             for k, v in sorted(
                                 comp_slopes.items(),
                                 key=lambda kv: -kv[1])},
        "debug_top_growers": (cap_dbg.get("top_growers") or [])[:5],
    }
    return leg


def retention():
    """`bench.py --retention`: the retention soak ledger. Legs and
    wall come from RETENTION_NS / RETENTION_WALL_S /
    RETENTION_SCRAPE_S (defaults n∈{3,8}, 60 s, 2 s) so CI can run the
    same shape it gates against the committed RETENTION_SMOKE.json.
    Emits one JSON payload; raw per-scrape rows land in
    RETENTION_OUT_DIR."""
    import tempfile

    ns = [int(x) for x in os.environ.get(
        "RETENTION_NS", "3,8").split(",") if x.strip()]
    wall_s = float(os.environ.get("RETENTION_WALL_S", "60"))
    scrape_s = float(os.environ.get("RETENTION_SCRAPE_S", "2.0"))
    out_dir = os.environ.get("RETENTION_OUT_DIR") or tempfile.mkdtemp(
        prefix="babble-retention-")
    os.makedirs(out_dir, exist_ok=True)
    ts_file = os.path.join(out_dir, "retention_timeseries.jsonl")
    payload = {
        "metric": "retention_soak",
        "unit": "bytes/event",
        "engine": "host",
        "store": "file",
        "runtime": _runtime_arg(),
        "wall_s_per_leg": wall_s,
        "timeseries_jsonl": ts_file,
        "cpus_effective": _cpus_effective(),
        "legs": {},
    }
    try:
        # The shared machine-speed yardstick (see bench_compare.py) —
        # only the ev/s context rows normalize by it; the byte slopes
        # are machine-independent ratios.
        calib_eps, _, _ = host_engine_events_per_sec(64, 5000)
        payload["host_events_per_s"] = round(calib_eps, 1)
        payload["host_events"] = 5000
    except Exception as exc:  # noqa: BLE001
        payload["calibration_error"] = str(exc)
    failures = 0
    for n in ns:
        log(f"retention leg n={n}: {wall_s:.0f}s wall, scrape every "
            f"{scrape_s:.1f}s")
        try:
            leg = retention_leg(n, wall_s, scrape_s, ts_file)
        except Exception as exc:  # noqa: BLE001
            payload[f"retention{n}_error"] = str(exc)
            failures += 1
            _emit(payload)
            continue
        payload["legs"][str(n)] = leg
        for k in ("events_per_s", "bytes_per_event",
                  "rss_slope_bytes_per_event",
                  "wal_slope_bytes_per_event", "top_grower"):
            if leg.get(k) is not None:
                payload[f"retention{n}_{k}"] = leg[k]
        log(f"  n={n}: {leg['events_per_s']:,.1f} ev/s, "
            f"{leg['bytes_per_event']} bytes/event, rss slope "
            f"{leg['rss_slope_bytes_per_event']}, wal slope "
            f"{leg['wal_slope_bytes_per_event']}, top grower "
            f"{leg['top_grower']}")
        _emit(payload)
    _emit(payload)
    return 1 if failures else 0


def child():
    import jax

    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    log(f"child up: backend={jax.default_backend()} "
        f"devices={[d.device_kind for d in jax.devices()]}")

    from babble_tpu.ops.dag import synthetic_dag

    ref_docker = 266.9  # reference docs/usage.rst:31-34 midpoint
    payload = {
        "metric": "consensus_events_per_s_n64",
        "value": 0.0,
        "unit": "events/s",
        "vs_baseline": 0.0,
        "baseline": "repo host engine, same topology (see host_* fields)",
        "ref_docker_events_per_s": ref_docker,
    }

    profile_dir = os.environ.get("BENCH_PROFILE_DIR")

    # -- stage 0: smoke ----------------------------------------------------
    log("stage smoke: n=8 e=256")
    dag, s_rank = synthetic_dag(8, 256, seed=0)
    best, _, _, n_cons, _ = time_pipeline(dag, s_rank, warm=1, reps=2)
    log(f"  smoke ok: {best * 1e3:.1f} ms, {n_cons} consensus events")
    payload["smoke_events_per_s"] = round(n_cons / best, 1)
    _emit(payload)

    # -- stage 1: headline n=64 e=50k -------------------------------------
    engine = "auto"
    if _budget_left() > 60:
        n, e = 64, 50_000
        log(f"stage headline: n={n} e={e}")
        t0 = time.monotonic()
        dag, s_rank = synthetic_dag(n, e, seed=1)
        log(f"  DAG gen {time.monotonic() - t0:.1f}s, "
            f"levels={dag.levels.shape}")
        engine = tune_engine(dag, s_rank)
        log(f"  tuned engine: {engine}")
        if profile_dir:
            jax.profiler.start_trace(profile_dir)
        best, med, times, n_cons, max_round = time_pipeline(
            dag, s_rank, reps=5, engine=engine)
        if profile_dir:
            jax.profiler.stop_trace()
        eps = n_cons / med
        log(f"  headline: median {med * 1e3:.1f} ms (best {best * 1e3:.1f}, "
            f"spread {min(times) * 1e3:.0f}-{max(times) * 1e3:.0f} ms) -> "
            f"{n_cons} consensus events ({eps:,.0f} ev/s median), "
            f"last round {max_round}")
        # The headline metric is the MEDIAN of 5 runs; best and the full
        # spread ride along (the shared chip varies +/-40% run to run).
        payload["value"] = round(eps, 1)
        payload["engine"] = engine
        payload["headline_ms"] = round(med * 1e3, 2)
        payload["headline_best_ms"] = round(best * 1e3, 2)
        payload["headline_best_events_per_s"] = round(n_cons / best, 1)
        payload["headline_spread_ms"] = [round(t * 1e3, 1) for t in times]
        payload["headline_consensus_events"] = n_cons
        _emit(payload)

    # -- stage 2: host-engine baseline, same topology ---------------------
    if _budget_left() > 60:
        # Size SWEEP: the device headline runs at e=50k but the host
        # engine would take minutes there, so the sweep measures how the
        # host's per-event cost moves with size — evidence for (not an
        # assumption of) the cross-size vs_baseline ratio. e must be
        # large enough that fame decides at n=64 (a round is ~700
        # events at this fan-out).
        sweep = {}
        for host_n_events in (2500, 5000, 10000):
            if sweep and _budget_left() < 2.5 * host_n_events / max(
                    min(sweep.values()), 1):
                break
            log(f"stage host baseline: n=64 e={host_n_events} "
                "(same topology family)")
            host_eps, host_done, _ = host_engine_events_per_sec(
                64, host_n_events)
            log(f"  host engine: {host_eps:,.0f} ev/s "
                f"({host_done} consensus)")
            sweep[host_n_events] = round(host_eps, 1)
        # vs_baseline stays pinned to the fixed e=5000 run so the ratio
        # is comparable across rounds; the sweep rides along as
        # evidence of how host cost moves with size.
        host_n_events = 5000 if 5000 in sweep else max(sweep)
        host_eps = sweep[host_n_events]
        payload["host_events_per_s"] = host_eps
        payload["host_events"] = host_n_events
        payload["host_sweep_events_per_s"] = sweep
        if payload["value"] and host_eps > 0:
            payload["vs_baseline"] = round(payload["value"] / host_eps, 1)
        _emit(payload)

    # -- stage 2b: sustained incremental ingest ---------------------------
    # The live-node metric: events arrive in sync-sized batches and each
    # batch re-runs consensus over the undecided tip (ops/incremental.py)
    # — the counterpart of the reference's per-sync RunConsensus
    # (node/core.go:277-296) rather than a one-shot full-DAG recompute.
    if _budget_left() > 120:
        from babble_tpu.ops.incremental import IncrementalEngine

        n, e_sus, bs = 64, 50_000, 4096
        log(f"stage sustained: n={n} e={e_sus} batch={bs} (pipelined)")
        dag_s, _ = synthetic_dag(n, e_sus, seed=3)
        eng = IncrementalEngine(
            n, capacity=65536, block=512, k_capacity=1024)
        import numpy as _np

        # PIPELINED driving — the same dispatch/collect overlap the
        # live node's consensus worker uses: append batch k+1 while
        # pass k computes on device, then collect k's commit delta and
        # dispatch k+1. Per-batch time is the HOST-BLOCKING wall
        # (append + collect wait + dispatch staging); the device
        # compute that overlapped the append no longer counts, which
        # is exactly the production hot path.
        phase_tot: dict = {}
        overlap_ns = 0
        prof_from = 3  # skip compile-warmup batches in the phase split

        def _harvest(b_i):
            nonlocal overlap_ns
            if b_i >= prof_from:
                for ph, ns in eng.phase_ns.items():
                    phase_tot[ph] = phase_tot.get(ph, 0) + ns
                overlap_ns += eng.last_overlap_ns

        t0 = time.perf_counter()
        per_batch = []
        pending = None
        b_i = 0
        k = 0
        while k < e_sus:
            hi = min(k + bs, e_sus)
            tb = time.perf_counter()
            eng.append_batch(
                dag_s.self_parent[k:hi], dag_s.other_parent[k:hi],
                dag_s.creator[k:hi], dag_s.index[k:hi], dag_s.coin[k:hi],
                _np.arange(k, hi))
            if pending is not None:
                eng.collect(pending)
                _harvest(b_i)
            pending = eng.dispatch()
            per_batch.append(time.perf_counter() - tb)
            b_i += 1
            k = hi
        if pending is not None:
            eng.collect(pending)
            _harvest(b_i)
        # Drain appends staged during the final in-flight pass.
        pending = eng.dispatch()
        if pending is not None:
            eng.collect(pending)
        total = time.perf_counter() - t0
        if e_sus % bs:  # final partial batch would skew the per-batch rate
            per_batch = per_batch[:-1]
        half = per_batch[len(per_batch) // 2:]
        steady = float(_np.median(half))
        log(f"  sustained: {total:.1f}s total ({e_sus / total:,.0f} ev/s), "
            f"steady {bs / steady:,.0f} ev/s "
            f"(per-batch spread {min(half):.2f}-{max(half):.2f}s), "
            f"{int((eng.rr[:e_sus] >= 0).sum())} consensus")
        payload["sustained_events_per_s"] = round(e_sus / total, 1)
        payload["sustained_steady_events_per_s"] = round(bs / steady, 1)
        payload["sustained_steady_spread_s"] = [
            round(min(half), 3), round(max(half), 3)]
        payload["sustained_batch"] = bs

        # Phase split of the pipelined loop: host-blocking ns per
        # phase (timers NOT synced — async dispatches only charge
        # their enqueue). The device->host trio (c_pull + coords +
        # fd_fold) is the share the tentpole targets: with the delta
        # pull overlapped it should be a small minority of pass wall.
        if phase_tot:
            # c_pull_wait/xfer are a SPLIT of c_pull, not siblings —
            # keep them out of the share denominator.
            _sub = ("c_pull_wait", "c_pull_xfer")
            top_t = {ph: ns for ph, ns in phase_tot.items()
                     if ph not in _sub}
            tot_ns = sum(top_t.values())
            shares = {ph: round(ns / tot_ns, 3)
                      for ph, ns in sorted(top_t.items())}
            bounding = max(top_t, key=top_t.get)
            pull_share = (shares.get("c_pull", 0) + shares.get("coords", 0)
                          + shares.get("fd_fold", 0))
            log(f"  phase split: " + ", ".join(
                f"{ph} {sh:.0%}" for ph, sh in shares.items())
                + f" -> bounded by {bounding}; "
                f"pull share {pull_share:.0%}, "
                f"overlap {overlap_ns / 1e9:.1f}s")
            payload["sustained_phase_share"] = shares
            payload["sustained_bounding_phase"] = bounding
            payload["sustained_pull_share"] = round(pull_share, 3)
            payload["sustained_overlap_s"] = round(overlap_ns / 1e9, 2)
            if phase_tot.get("c_pull"):
                # Wait (device still computing) vs xfer (D2H copy) —
                # the attribution that says whether c_pull needs a
                # faster kernel or a smaller pull.
                payload["sustained_c_pull_split"] = {
                    "wait": round(phase_tot.get("c_pull_wait", 0)
                                  / phase_tot["c_pull"], 3),
                    "xfer": round(phase_tot.get("c_pull_xfer", 0)
                                  / phase_tot["c_pull"], 3)}

        # Device-time attribution in a SEPARATE short pass (synced
        # per-phase timers serialize every stage, so they must not run
        # inside the timed loop): a fresh engine replays the first 6
        # batches synchronously with compile caches warm — answering
        # which DEVICE stage is the biggest compute, independent of
        # what the pipeline hides from the host.
        prof = IncrementalEngine(n, capacity=65536, block=512,
                                 k_capacity=1024)
        os.environ["BABBLE_ENGINE_TIMERS"] = "1"
        phase_sync: dict = {}
        k = 0
        for p_i in range(min(6, len(per_batch))):
            hi = min(k + bs, e_sus)
            prof.append_batch(
                dag_s.self_parent[k:hi], dag_s.other_parent[k:hi],
                dag_s.creator[k:hi], dag_s.index[k:hi], dag_s.coin[k:hi],
                _np.arange(k, hi))
            prof.run()
            if p_i >= 3:  # skip warmup batches
                for ph, ns in prof.phase_ns.items():
                    phase_sync[ph] = phase_sync.get(ph, 0) + ns
            k = hi
        os.environ.pop("BABBLE_ENGINE_TIMERS", None)
        if phase_sync:
            top_s = {ph: ns for ph, ns in phase_sync.items()
                     if ph not in ("c_pull_wait", "c_pull_xfer")}
            tot_ns = sum(top_s.values())
            payload["sustained_phase_share_synced"] = {
                ph: round(ns / tot_ns, 3)
                for ph, ns in sorted(top_s.items())}
        _emit(payload)

    on_cpu = jax.default_backend() == "cpu"

    # -- stage 2c: the real gossiping node --------------------------------
    # 4 live nodes (threads, inmem transport, per-event ECDSA, the full
    # sync protocol) — the apples-to-apples number against the
    # reference's 4-node docker steady state (265.53-268.27 ev/s,
    # reference docs/usage.rst:31-34). Two rows: the host engine (the
    # like-for-like configuration — 4 independent consensus engines on
    # one machine, as the reference runs), and the TPU engine, where
    # all 4 nodes time-share ONE tunneled chip (~90 ms per device sync)
    # — honest, but hardware-limited in a way a per-validator
    # accelerator deployment is not.
    if os.environ.get("BENCH_SKIP_NODE") != "1":
        if _budget_left() > 180:
            try:
                node_eps, node_ph = node_testnet_events_per_sec(
                    engine="host", warm_s=30.0, window_s=30.0)
                log(f"  4-node --engine host testnet: {node_eps:,.1f} "
                    f"committed events/s (ref docker: {ref_docker})")
                payload["node_events_per_s"] = round(node_eps, 1)
                payload["node_vs_ref_docker"] = round(
                    node_eps / ref_docker, 2)
                payload["node_phase_share"] = node_ph.get("phase_share")
                payload["node_ingest_phase_share"] = node_ph.get(
                    "ingest_phase_share")
                payload["commit_latency_p50_ms"] = node_ph.get(
                    "commit_latency_p50_ms")
                payload["commit_latency_p99_ms"] = node_ph.get(
                    "commit_latency_p99_ms")
                _emit(payload)
            except Exception as exc:  # noqa: BLE001
                log(f"  node host stage failed: {exc}")
        if _budget_left() > 150:
            try:
                # Durable-path A/B: the same host testnet on WAL-backed
                # FileStores. store_commit_share = fraction of node
                # phase wall inside sqlite COMMITs; the events/s delta
                # vs node_events_per_s is the full durable overhead.
                file_eps, file_ph = node_testnet_events_per_sec(
                    engine="host", warm_s=30.0, window_s=30.0,
                    store="file")
                log(f"  4-node --engine host --store file testnet: "
                    f"{file_eps:,.1f} committed events/s "
                    f"(store_commit_share "
                    f"{file_ph.get('store_commit_share')})")
                payload["node_file_events_per_s"] = round(file_eps, 1)
                payload["store_commit_share"] = file_ph.get(
                    "store_commit_share")
                _emit(payload)
            except Exception as exc:  # noqa: BLE001
                log(f"  node file-store stage failed: {exc}")
        if _budget_left() > 520 and not on_cpu:
            try:
                # The warm gate shrank 6000 -> 2500 committed events:
                # engine prewarm compiles the kernel ladder at node
                # construction and the persistent compile cache covers
                # restarts, so the old multi-thousand-event drift of
                # window-shape compiles is mostly gone.
                node_eps, node_ph = node_testnet_events_per_sec(
                    engine="tpu", warm_s=180.0, window_s=40.0,
                    warm_gate_events=2500, windows=3)
                log(f"  4-node --engine tpu testnet (one shared chip): "
                    f"{node_eps:,.1f} committed events/s; "
                    f"phases {node_ph}")
                payload["node_tpu_events_per_s"] = round(node_eps, 1)
                payload["node_tpu_phase_share"] = node_ph.get(
                    "phase_share")
                payload["node_tpu_engine_phase_share"] = node_ph.get(
                    "engine_phase_share")
                payload["node_tpu_engine_pull_share"] = node_ph.get(
                    "engine_pull_share")
                payload["node_tpu_engine_overlap_s"] = node_ph.get(
                    "engine_overlap_s")
                _emit(payload)
            except Exception as exc:  # noqa: BLE001
                log(f"  node tpu stage failed: {exc}")
        # 16 validators on one machine — 4x the reference's published
        # deployment size, host engine (16 independent engines).
        if _budget_left() > 150:
            try:
                node_eps, _ = node_testnet_events_per_sec(
                    engine="host", n_nodes=16, warm_s=45.0, window_s=30.0,
                    interval=1.0)
                log(f"  16-node --engine host testnet: {node_eps:,.1f} "
                    f"committed events/s")
                payload["node16_events_per_s"] = round(node_eps, 1)
                # Machine-tracked cluster-scaling trend (node{4,16}
                # here, node{3,16} in the smoke payload): the ledger
                # charts whether per-node throughput scales out or
                # collapses with cluster size.
                if "node_events_per_s" in payload:
                    payload["node_scaling_events_per_s"] = {
                        "4": payload["node_events_per_s"],
                        "16": round(node_eps, 1)}
                _emit(payload)
            except Exception as exc:  # noqa: BLE001
                log(f"  node 16 stage failed: {exc}")

    # -- stage 3: north star n=1024 e=100k --------------------------------
    # Skipped on the CPU fallback: at this size a host CPU cannot finish
    # inside any reasonable budget, and the number is only meaningful on
    # the chip (BASELINE.md north-star target).
    force_ns = os.environ.get("BENCH_FORCE_NORTHSTAR") == "1"
    if _budget_left() > 300 and (not on_cpu or force_ns):
        n, e = 1024, 100_000
        log(f"stage northstar: n={n} e={e}")
        t0 = time.monotonic()
        dag, s_rank = synthetic_dag(n, e, seed=2)
        log(f"  DAG gen {time.monotonic() - t0:.1f}s, "
            f"levels={dag.levels.shape}")
        try:
            # Engine choice flips with n (the frontier sweep's trip
            # count is the round count, which shrinks as n grows), so
            # re-tune at this size instead of reusing the headline's.
            engine_ns = tune_engine(dag, s_rank)
            log(f"  tuned northstar engine: {engine_ns}")
            best, med, times, n_cons, max_round = time_pipeline(
                dag, s_rank, warm=1, reps=3, engine=engine_ns)
            eps = n_cons / med
            log(f"  northstar: median {med * 1e3:.1f} ms "
                f"(spread {min(times) * 1e3:.0f}-{max(times) * 1e3:.0f}) -> "
                f"{n_cons} consensus ({eps:,.0f} ev/s), "
                f"last round {max_round}")
            payload["northstar_events_per_s"] = round(eps, 1)
            payload["northstar_best_events_per_s"] = round(n_cons / best, 1)
            payload["northstar_spread_ms"] = [
                round(t * 1e3, 1) for t in times]
            payload["northstar_n"] = n
            payload["northstar_events"] = e
            _emit(payload)

            # North-star INCREMENTAL: the engine a live `--engine tpu`
            # node actually drives (ops/incremental.py), fed the same
            # DAG in sync-sized batches — the validated at-scale number
            # VERDICT r3 asked for (run on the real chip, value-pulling
            # every sync).
            if _budget_left() > 240:
                from babble_tpu.ops.incremental import IncrementalEngine
                import numpy as _np

                bs_ns = 4096
                log(f"stage northstar incremental: n={n} e={e} "
                    f"batch={bs_ns}")
                eng = IncrementalEngine(
                    n, capacity=131072, block=512, k_capacity=512)
                t0 = time.perf_counter()
                per_b = []
                k = 0
                while k < e:
                    hi = min(k + bs_ns, e)
                    eng.append_batch(
                        dag.self_parent[k:hi], dag.other_parent[k:hi],
                        dag.creator[k:hi], dag.index[k:hi],
                        dag.coin[k:hi], _np.arange(k, hi))
                    tb = time.perf_counter()
                    eng.run()
                    per_b.append(time.perf_counter() - tb)
                    k = hi
                total_ns = time.perf_counter() - t0
                half = per_b[len(per_b) // 2:]
                steady_ns = float(_np.median(half))
                n_cons_inc = int((eng.rr[:e] >= 0).sum())
                log(f"  northstar incremental: {total_ns:.1f}s "
                    f"({e / total_ns:,.0f} ev/s), steady "
                    f"{bs_ns / steady_ns:,.0f} ev/s, "
                    f"{n_cons_inc} consensus")
                payload["northstar_incremental_events_per_s"] = round(
                    e / total_ns, 1)
                payload["northstar_incremental_steady_events_per_s"] = (
                    round(bs_ns / steady_ns, 1))
                _emit(payload)

            # Honest wall-clock multiple at this scale (BASELINE.md
            # driver target: >=100x at n=1024/100k): the host engine
            # reaches no consensus below ~3n events per round, so its
            # per-event processing rate (insert + consensus pass) over
            # a 2k-event prefix is measured and extrapolated to the
            # full run — labeled as such.
            if _budget_left() > 120:
                host_e = 2000
                log(f"stage northstar host extrapolation: n={n} e={host_e}")
                # only the insert+consensus span counts (key generation
                # and event signing setup are excluded on both sides)
                _, _, host_dt = host_engine_events_per_sec(n, host_e)
                host_rate = host_e / host_dt
                extrapolated = e / host_rate
                payload["northstar_host_rate_events_per_s"] = round(
                    host_rate, 1)
                payload["northstar_host_wall_extrapolated_s"] = round(
                    extrapolated, 1)
                payload["northstar_wall_speedup_vs_host"] = round(
                    extrapolated / best, 1)
                log(f"  host rate {host_rate:.1f} ev/s -> extrapolated "
                    f"{extrapolated:,.0f}s vs device {best:.1f}s "
                    f"({extrapolated / best:,.0f}x)")
                _emit(payload)

            # vs-Go calibration (BASELINE.json's target names Go, not
            # Python): build and run the C++ conservative stand-in for
            # the reference engine's data path (cpp/ref_model_bench.cc
            # — flat int-indexed storage, no GC, no signatures, fame
            # and FindOrder omitted; every choice makes it FASTER than
            # real Go), and report the resulting LOWER bound on the
            # device-vs-Go wall-clock multiple. The Python-host
            # extrapolation above brackets it from the other side.
            if _budget_left() > 120:
                try:
                    src = os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "cpp", "ref_model_bench.cc")
                    binp = os.path.join(CACHE_DIR, "ref_model_bench")
                    stale = (not os.path.exists(binp)
                             or os.path.getmtime(binp)
                             < os.path.getmtime(src))
                    if stale:
                        subprocess.run(
                            ["g++", "-O3", "-march=native", "-o", binp,
                             src], check=True, timeout=120)
                    out = subprocess.run(
                        [binp, str(n), str(e)], capture_output=True,
                        timeout=1200, check=True)
                    model = json.loads(out.stdout)
                    model_wall = float(model["wall_s"])
                    vs_go_min = model_wall / best
                    payload["vs_go_model_wall_s"] = round(model_wall, 2)
                    payload["vs_go_estimated_min"] = round(vs_go_min, 1)
                    payload["vs_go_basis"] = (
                        "lower bound: wall of a C++ reimplementation "
                        "of the reference insert+DivideRounds data "
                        "path (cpp/ref_model_bench.cc), strictly "
                        "faster than Go (no GC/strings/signatures, "
                        "fame+order omitted), vs the device one-shot")
                    log(f"  vs-Go: C++ model {model_wall:,.1f}s vs "
                        f"device {best:.1f}s -> >= {vs_go_min:,.0f}x "
                        f"(conservative lower bound)")
                    _emit(payload)
                except Exception as exc:  # noqa: BLE001
                    log(f"  vs-Go calibration failed: {exc}")
        except Exception as exc:  # noqa: BLE001
            log(f"  northstar failed: {exc}")

    _emit(payload)


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    elif "--node-smoke" in sys.argv:
        sys.exit(node_smoke())
    elif "--trace-overhead" in sys.argv:
        sys.exit(trace_overhead())
    elif "--health-overhead" in sys.argv:
        sys.exit(health_overhead())
    elif "--gossip-overhead" in sys.argv:
        sys.exit(gossip_overhead())
    elif "--profile-overhead" in sys.argv:
        sys.exit(profile_overhead())
    elif "--verify-bench" in sys.argv:
        sys.exit(verify_bench())
    elif "--ingress-overhead" in sys.argv:
        sys.exit(ingress_overhead())
    elif "--loadgen" in sys.argv:
        sys.exit(loadgen())
    elif "--soak" in sys.argv:
        sys.exit(gossip_soak())
    elif "--capacity-overhead" in sys.argv:
        sys.exit(capacity_overhead())
    elif "--retention" in sys.argv:
        sys.exit(retention())
    else:
        main()
