"""Utility-layer tests — mirrors reference common/lru_test.go and
common/rolling_index_test.go (incl. TooLate/skip semantics)."""

import pytest

from babble_tpu.common import LRU, RollingIndex, StoreError, StoreErrType, is_store_err


def test_lru_basic():
    evicted = []
    lru = LRU(2, on_evict=lambda k, v: evicted.append(k))
    assert not lru.add("a", 1)
    assert not lru.add("b", 2)
    v, ok = lru.get("a")
    assert ok and v == 1
    # "b" is now LRU; adding "c" evicts it
    assert lru.add("c", 3)
    assert evicted == ["b"]
    _, ok = lru.get("b")
    assert not ok
    assert len(lru) == 2
    assert lru.keys() == ["a", "c"]


def test_lru_update_refreshes():
    lru = LRU(2)
    lru.add("a", 1)
    lru.add("b", 2)
    lru.add("a", 10)  # refresh
    lru.add("c", 3)  # evicts b
    assert lru.contains("a") and lru.contains("c") and not lru.contains("b")
    v, _ = lru.get("a")
    assert v == 10


def test_rolling_index_window():
    size = 10
    ri = RollingIndex(size)
    items = [f"item{i}" for i in range(9)]
    for i, it in enumerate(items):
        ri.add(it, i)
    cached, last = ri.get_last_window()
    assert last == 8
    assert list(cached) == items

    # get with skip
    assert ri.get(4) == items[5:]
    assert ri.get(8) == []
    assert ri.get(100) == []


def test_rolling_index_roll_and_too_late():
    size = 2
    ri = RollingIndex(size)
    for i in range(2 * size + 1):  # forces one roll
        ri.add(i, i)
    # window now holds indexes 2..4
    with pytest.raises(StoreError) as ei:
        ri.get(0)
    assert is_store_err(ei.value, StoreErrType.TOO_LATE)
    assert ri.get(1) == [2, 3, 4]

    with pytest.raises(StoreError) as ei:
        ri.get_item(1)
    assert is_store_err(ei.value, StoreErrType.TOO_LATE)
    assert ri.get_item(3) == 3
    with pytest.raises(StoreError) as ei:
        ri.get_item(10)
    assert is_store_err(ei.value, StoreErrType.KEY_NOT_FOUND)


def test_rolling_index_add_errors():
    ri = RollingIndex(5)
    ri.add("a", 0)
    with pytest.raises(StoreError) as ei:
        ri.add("dup", 0)
    assert is_store_err(ei.value, StoreErrType.PASSED_INDEX)
    with pytest.raises(StoreError) as ei:
        ri.add("skip", 2)
    assert is_store_err(ei.value, StoreErrType.SKIPPED_INDEX)
    ri.add("b", 1)
