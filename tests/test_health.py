"""Consensus health plane tests (docs/observability.md "Consensus
health"): the committed-block hash chain, the divergence sentinel's
live detection in a 3-node net (fork index named within one gossip
round), the stall watchdog's diagnosis + self-clear, the DAG
inspector endpoint, the dagdump DOT renderer, the wire sidecar's
legacy byte-identity, the SpanRing drop counter, and promtext's
labeled --require matchers."""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from babble_tpu.hashgraph import Block, InmemStore
from babble_tpu.hashgraph.health import BlockHashChain
from babble_tpu.net import FaultyTransport, InmemTransport
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.net.transport import SyncRequest, SyncResponse
from babble_tpu.node import Node
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.node.health import DivergenceSentinel
from babble_tpu.proxy import InmemAppProxy
from babble_tpu.telemetry import Registry, SpanRing, promtext
from babble_tpu.telemetry.dagdump import render_dot

from test_node import check_gossip, make_keyed_peers

CACHE = 10000


def _blocks(n, tag=""):
    return [Block(r, [f"tx{tag}{r}".encode()]) for r in range(1, n + 1)]


# ----------------------------------------------------- chain (unit)


def test_chain_hash_deterministic_and_ordered():
    a, b = BlockHashChain(), BlockHashChain()
    for blk in _blocks(5):
        a.advance(blk)
        b.advance(blk)
    assert a.hash == b.hash and a.index == b.index == 4
    assert a.base_round == 1 and a.round == 5
    # Same blocks, different order => different chain (the whole
    # point: the hash covers ORDER, not just membership).
    c = BlockHashChain()
    blocks = _blocks(5)
    for blk in [blocks[1], blocks[0]] + blocks[2:]:
        c.advance(blk)
    assert c.hash != a.hash


def test_chain_corrupt_hook_diverges_from_that_block_on():
    a, b = BlockHashChain(), BlockHashChain()
    blocks = _blocks(6)
    for blk in blocks[:3]:
        a.advance(blk)
        b.advance(blk)
    b.corrupt_next()
    for blk in blocks[3:]:
        a.advance(blk)
        b.advance(blk)
    # Links before the corruption agree; every link after differs.
    for i in range(3):
        assert a.lookup(i)[2] == b.lookup(i)[2]
    for i in range(3, 6):
        assert a.lookup(i)[2] != b.lookup(i)[2]


def test_chain_state_round_trip_and_rebase():
    a = BlockHashChain()
    for blk in _blocks(4):
        a.advance(blk)
    b = BlockHashChain()
    b.restore(a.state())
    assert b.hash == a.hash and b.index == a.index
    assert b.base_round == a.base_round
    # The restored chain continues identically.
    a.advance(Block(9, [b"x"]))
    b.advance(Block(9, [b"x"]))
    assert a.hash == b.hash
    b.rebase()
    assert b.index == -1 and b.base_round == -1
    assert "Index" not in b.claim()


# ------------------------------------------------- sentinel (unit)


def _sentinel(label="0"):
    import logging

    return DivergenceSentinel(Registry(), label,
                              logging.getLogger("test"))


def test_sentinel_agreement_and_divergence_with_exact_fork_index():
    s0, s1 = _sentinel("0"), _sentinel("1")
    blocks = _blocks(6)
    for blk in blocks[:3]:
        s0.chain.advance(blk)
        s1.chain.advance(blk)
    s0.observe("peer1", s1.claim(3))
    assert s0.divergence_count() == 0
    assert s0.peer_progress()["peer1"]["last_agreed_index"] == 2
    assert s0.peer_progress()["peer1"]["last_known_round"] == 3
    # Node 1's stream corrupts at block index 3; detection must name
    # exactly that index (the short-hash window brackets it).
    s1.chain.corrupt_next()
    for blk in blocks[3:]:
        s0.chain.advance(blk)
        s1.chain.advance(blk)
    s0.observe("peer1", s1.claim(6))
    assert s0.divergence_count() == 1
    (report,) = s0.reports
    assert report["fork_index"] == 3
    assert report["fork_round"] == 4  # blocks are rounds 1..6
    assert report["last_agreed_index"] == 2
    # Repeated observations keep counting but do not re-report.
    s0.observe("peer1", s1.claim(6))
    assert s0.divergence_count() == 2
    assert len(s0.reports) == 1


def test_sentinel_ignores_malformed_peer_claims():
    """Claims come from untrusted peers: garbage must be dropped, not
    thrown into the gossip path."""
    s = _sentinel()
    for blk in _blocks(3):
        s.chain.advance(blk)
    for bad in (None, "junk", 42,
                {"CRound": "x"},
                {"CRound": 1, "Index": 2, "Base": 1},  # no Hash
                {"CRound": 1, "Index": 2, "Base": 1, "Hash": "ab",
                 "Window": "nope"},
                {"CRound": 1, "Index": "2", "Base": 1, "Hash": "ab",
                 "Window": [[1]]}):
        s.observe("peerX", bad)  # must not raise
    assert s.divergence_count() == 0
    assert s.reports == []


def test_sentinel_skips_rebased_segments():
    s0, s1 = _sentinel("0"), _sentinel("1")
    for blk in _blocks(3):
        s0.chain.advance(blk)
    # s1 fast-forwarded: its segment starts at round 5 — different
    # base, so no comparison and no false alarm either way.
    s1.rebase()
    for blk in [Block(5, [b"a"]), Block(6, [b"b"])]:
        s1.chain.advance(blk)
    s0.observe("peer1", s1.claim(2))
    s1.observe("peer0", s0.claim(3))
    assert s0.divergence_count() == 0
    assert s1.divergence_count() == 0
    # Progress tracking still works across segments.
    assert s0.peer_progress()["peer1"]["last_known_round"] == 2


# ------------------------------------------------- wire sidecar


def test_health_sidecar_absent_is_byte_identical_legacy_wire():
    """Pinned like _TraceID: no sentinel => the exact legacy dicts."""
    req = SyncRequest(3, {0: 4, 1: -1})
    assert req.to_dict() == {"FromID": 3, "Known": {"0": 4, "1": -1}}
    resp = SyncResponse(2, known={0: 1})
    assert resp.to_dict() == {
        "FromID": 2, "SyncLimit": False, "Events": [],
        "Known": {"0": 1}}
    # With the sidecar set, exactly one extra key rides along and
    # round-trips; legacy decoders ignore it.
    claim = {"CRound": 7, "Base": 1, "Index": 2, "Round": 5,
             "Hash": "ab" * 32, "Window": [[2, "ab" * 8]]}
    req.health = claim
    d = req.to_dict()
    assert d["Health"] == claim
    assert SyncRequest.from_dict(json.loads(json.dumps(d))).health == claim
    resp.health = claim
    d = resp.to_dict()
    assert SyncResponse.from_dict(
        json.loads(json.dumps(d))).health == claim


def test_health_sidecar_rides_columnar_tcp_framing():
    from babble_tpu.net.tcp_transport import (
        _pack_sync_response, _unpack_sync_response)

    claim = {"CRound": 4, "Base": 0, "Index": 1, "Round": 3,
             "Hash": "cd" * 32, "Window": [[1, "cd" * 8]]}
    resp = SyncResponse(1, known={0: 2}, health=claim)
    out = _unpack_sync_response(_pack_sync_response(resp))
    assert out.health == claim
    assert out.known == {0: 2}


# ------------------------------------------------- live 3-node net


def _make_net(n=3, heartbeat=0.01, chaos=False, conf_hook=None):
    inner = [InmemTransport(f"addr{i}", timeout=2.0) for i in range(n)]
    connect_all(inner)
    if chaos:
        trans = {t.local_addr(): FaultyTransport(t, seed=11)
                 for t in inner}
    else:
        trans = {t.local_addr(): t for t in inner}
    entries = make_keyed_peers(n, addr_fn=lambda i: f"addr{i}")
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    nodes, keys = [], []
    for i, (key, peer) in enumerate(entries):
        conf = fast_config(heartbeat=heartbeat)
        if conf_hook is not None:
            conf_hook(conf)
        store = InmemStore(participants, CACHE)
        node = Node(conf, i, key, peers, store,
                    trans[peer.net_addr], InmemAppProxy())
        node.init()
        nodes.append(node)
        keys.append(key)
    return nodes, keys, trans


def _drive(nodes, predicate, timeout, submit_to=None, tag="health"):
    active = submit_to if submit_to is not None else nodes
    deadline = time.monotonic() + timeout
    i = 0
    while time.monotonic() < deadline:
        active[i % len(active)].submit_tx(f"{tag} tx {i}".encode())
        i += 1
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError("timeout waiting for predicate")


def test_live_divergence_detection_names_fork_index():
    """Acceptance: a deliberately corrupted block stream (test hook)
    on one node of a live 3-node net is detected — by its peers and by
    itself — within one gossip round of the next piggybacked claim,
    naming the fork index."""
    nodes, _keys, _ = _make_net(3)
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        # Honest warmup: everyone commits blocks, claims agree.
        _drive(nodes, lambda: all(
            nd.sentinel.chain.index >= 1 for nd in nodes), 60.0)
        assert all(nd.sentinel.divergence_count() == 0 for nd in nodes)
        bad = nodes[2]
        fork_index = bad.sentinel.chain.corrupt_next()

        def detected():
            # Wait for an HONEST node to flag the corrupted peer (the
            # corrupt node also reports its peers, symmetrically, but
            # the acceptance is peers catching the bad stream).
            return any(r["peer"] == "addr2"
                       for nd in nodes[:2] for r in nd.sentinel.reports)

        _drive(nodes, detected, 60.0)
        reports = [r for nd in nodes for r in nd.sentinel.reports]
        # Every report names the corrupted chain position exactly —
        # the short-hash window pins the first diverged index.
        assert all(r["fork_index"] == fork_index for r in reports), (
            f"expected fork at {fork_index}, got {reports}")
        honest = [r for nd in nodes[:2] for r in nd.sentinel.reports]
        assert any(r["peer"] == "addr2" for r in honest)
    finally:
        for nd in nodes:
            nd.shutdown()


def test_stall_watchdog_diagnoses_silenced_creator_and_clears():
    """Acceptance: with one of 3 creators silenced (crashed chaos
    transport) no round can decide (supermajority = 3); the watchdog
    names the stuck round, its undecided witnesses, and the silent
    creator — and clears once the partition heals."""
    nodes, _keys, trans = _make_net(
        3, chaos=True,
        conf_hook=lambda c: setattr(c, "stall_timeout", 1.0))
    addr = {i: nodes[i].local_addr for i in range(3)}
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        _drive(nodes, lambda: all(
            (nd.core.get_last_consensus_round_index() or 0) >= 2
            for nd in nodes), 90.0)
        assert nodes[0].watchdog.diagnosis is None

        trans[addr[2]].crash()
        survivors = nodes[:2]

        def stalled():
            return nodes[0].watchdog.diagnosis is not None

        _drive(nodes, stalled, 45.0, submit_to=survivors)
        d = nodes[0].watchdog.describe()
        lcr = nodes[0].core.get_last_consensus_round_index()
        assert d["stalled"] is True
        assert d["last_consensus_round"] == lcr
        assert d["undecided_rounds"], "diagnosis names no round"
        stuck = d["undecided_rounds"][0]
        assert stuck["round"] > lcr
        assert stuck["undecided_witnesses"] > 0
        assert stuck["undecided"], "no undecided witnesses named"
        silent_ids = [c["creator_id"] for c in d["silent_creators"]]
        bad_pid = nodes[2].core.participants[nodes[2].core.hex_id()]
        assert bad_pid in silent_ids, (
            f"silenced creator {bad_pid} not in {silent_ids}")
        # The stall flag reaches /Stats and the gauges.
        assert nodes[0].get_stats()["stalled"] == "True"

        # Heal: rounds decide again, diagnosis clears itself.
        trans[addr[2]].restore()
        target = (lcr or 0) + 2

        def cleared():
            return (nodes[0].watchdog.diagnosis is None
                    and (nodes[0].core.get_last_consensus_round_index()
                         or 0) >= target)

        _drive(nodes, cleared, 90.0)
        assert nodes[0].watchdog.describe()["stalled"] is False
    finally:
        for nd in nodes:
            nd.shutdown()
    check_gossip(nodes[:2])


def test_dag_inspector_endpoint_and_dagdump_renders_valid_dot():
    """Acceptance: /debug/hashgraph exports a >=2-round window from a
    live node; dagdump renders it to structurally valid DOT. Also
    exercises /debug/consensus and the /debug/peers progress columns
    off the same run."""
    from babble_tpu.service import Service

    nodes, _keys, _ = _make_net(3)
    svc = Service("127.0.0.1:0", nodes[0])
    svc.serve_async()
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        _drive(nodes, lambda: all(
            (nd.core.get_last_consensus_round_index() or 0) >= 3
            for nd in nodes), 90.0)

        with urllib.request.urlopen(
                f"http://{svc.addr}/debug/hashgraph?from=0",
                timeout=10) as r:
            window = json.loads(r.read())
        assert window["to_round"] - window["from_round"] + 1 >= 2
        assert len(window["events"]) > 5
        sample = window["events"][0]
        for key in ("hash", "creator_id", "index", "self_parent",
                    "other_parent", "round", "witness", "famous",
                    "round_received"):
            assert key in sample
        assert any(e["witness"] for e in window["events"])
        assert any(e["round_received"] is not None
                   for e in window["events"])

        dot = render_dot(window, title="test")
        assert dot.startswith('digraph "test" {')
        assert dot.rstrip().endswith("}")
        assert dot.count("{") == dot.count("}")
        assert "->" in dot and "style=dashed" in dot
        assert "subgraph cluster_0" in dot
        # Edge endpoints reference declared nodes only.
        declared = {ln.split()[0] for ln in dot.splitlines()
                    if ln.strip().startswith("e") and "[" in ln}
        for ln in dot.splitlines():
            if "->" in ln:
                a, b = ln.strip().rstrip(";").split(" -> ")
                assert a in declared and b.split(" ")[0] in declared

        # The CLI round-trips through a file.
        import subprocess
        import sys
        import tempfile

        with tempfile.NamedTemporaryFile(
                "w", suffix=".json", delete=False) as f:
            json.dump(window, f)
        out = subprocess.run(
            [sys.executable, "-m", "babble_tpu.telemetry.dagdump",
             f.name], capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert out.stdout.startswith("digraph")

        with urllib.request.urlopen(
                f"http://{svc.addr}/debug/consensus", timeout=10) as r:
            health = json.loads(r.read())
        assert health["sentinel"]["chain"]["index"] >= 0
        assert health["sentinel"]["divergences"] == 0
        assert health["progress"]["last_consensus_round"] >= 3
        assert health["stall"]["stalled"] is False
        assert health["forks"]["detected"] == 0

        with urllib.request.urlopen(
                f"http://{svc.addr}/debug/peers", timeout=10) as r:
            peers = json.loads(r.read())
        assert "round_lag" in peers and "last_consensus_round" in peers
        assert any("behind_by" in p for p in peers["peers"].values())
    finally:
        for nd in nodes:
            nd.shutdown()
        svc.close()


# ------------------------------------------------- satellites


def test_span_ring_counts_drops_and_reports_in_dump():
    ring = SpanRing(4)
    for k in range(7):
        ring.record(f"s{k}", 0, 1)
    assert ring.dropped == 3
    assert len(ring) == 4
    dump = ring.to_chrome_trace(pid=1)
    assert dump["babble"]["dropped"] == 3
    ring.flow("s", 42)
    assert ring.dropped == 4
    # Disabled ring: never drops, never counts.
    off = SpanRing(0)
    off.record("x", 0, 1)
    assert off.dropped == 0


def test_promtext_require_label_matchers():
    text = (
        "# TYPE babble_forks_total counter\n"
        'babble_forks_total{node="0"} 0\n'
        'babble_forks_total{creator="0xAB",node="1"} 2\n'
        "# TYPE babble_phase_seconds histogram\n"
        'babble_phase_seconds_bucket{phase="sync",le="+Inf"} 1\n'
        'babble_phase_seconds_sum{phase="sync"} 0.5\n'
        'babble_phase_seconds_count{phase="sync"} 1\n')
    samples, _ = promtext.parse(text)
    assert promtext.check_series(samples, ["babble_forks_total"]) == []
    assert promtext.check_series(
        samples, ['babble_forks_total{creator="0xAB"}']) == []
    assert promtext.check_series(
        samples, ['babble_forks_total{creator="0xAB",node="1"}']) == []
    missing = promtext.check_series(
        samples, ['babble_forks_total{creator="0xZZ"}'])
    assert missing == ['babble_forks_total{creator="0xZZ"}']
    # Histograms match through their _count series.
    assert promtext.check_series(
        samples, ['babble_phase_seconds{phase="sync"}']) == []
    assert promtext.check_series(
        samples, ['babble_phase_seconds{phase="nope"}'])
    with pytest.raises(ValueError):
        promtext.check_series(samples, ["babble_forks_total{creator}"])


def test_promtext_cli_accepts_label_matchers(monkeypatch):
    import io

    text = ('# TYPE babble_forks_total counter\n'
            'babble_forks_total{creator="0xAB"} 1\n')
    monkeypatch.setattr("sys.stdin", io.StringIO(text))
    assert promtext.main(
        ["--require", 'babble_forks_total{creator="0xAB"}']) == 0
    monkeypatch.setattr("sys.stdin", io.StringIO(text))
    assert promtext.main(
        ["--require", 'babble_forks_total{creator="0xZZ"}']) == 1
    monkeypatch.setattr("sys.stdin", io.StringIO(text))
    assert promtext.main(["--require", "babble{bad"]) == 1
