"""Pallas strongly-see kernel: bit parity with the XLA formulation
(interpreter mode on the virtual CPU mesh), standalone and wired into
decide_fame via BABBLE_PALLAS=1."""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from babble_tpu.ops.pallas_kernels import strongly_see_counts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize(
    "m,w,n", [(5, 7, 4), (64, 64, 64), (130, 200, 100)],
    ids=["tiny", "square", "ragged"],
)
def test_strongly_see_counts_parity(m, w, n):
    rng = np.random.default_rng(3)
    la = rng.integers(-1, 50, (m, n)).astype(np.int32)
    fd = rng.integers(0, 50, (w, n)).astype(np.int32)
    fd[rng.random((w, n)) < 0.2] = np.iinfo(np.int32).max  # unreached
    got = np.asarray(strongly_see_counts(la, fd, interpret=True))
    want = (la[:, None, :] >= fd[None, :, :]).sum(-1, dtype=np.int32)
    assert (got == want).all()


@pytest.mark.slow
def test_decide_fame_with_pallas_matches():
    """decide_fame with BABBLE_PALLAS=1 (fresh process: the flag is read
    at trace time) equals the default XLA path on a synthetic DAG."""
    child = r"""
import sys
sys.path.insert(0, %(repo)r)
from babble_tpu.devices import ensure_virtual_devices
ensure_virtual_devices(1)
import numpy as np
from babble_tpu.ops.dag import synthetic_dag
from babble_tpu.ops.pipeline import run_pipeline
dag, _ = synthetic_dag(8, 400, seed=17)
out = run_pipeline(dag, engine="wavefront")
np.save("%(out)s", np.asarray(out[3]))
"""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        results = {}
        for flag in ("0", "1"):
            path = os.path.join(td, f"famous{flag}.npy")
            env = dict(os.environ)
            env["BABBLE_PALLAS"] = flag
            env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
            out = subprocess.run(
                [sys.executable, "-c", child % {"repo": REPO, "out": path}],
                capture_output=True, text=True, timeout=300, env=env,
            )
            assert out.returncode == 0, out.stderr[-2000:]
            results[flag] = np.load(path)
        assert (results["0"] == results["1"]).all()
