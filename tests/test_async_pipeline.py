"""Async consensus pipeline parity: the dispatch/collect split with
its double-buffered staging must be invisible in the results.

The seams where silent divergence would hide are (a) appends landing
while a pass is in flight (the second staging buffer), (b) capacity /
chain-bucket regrowth crossing a dispatch boundary, and (c) the
window-overflow redo path re-dispatching from a PendingPass snapshot.
Each test drives those seams and asserts byte-identical consensus
results against an oracle: the one-shot device pipeline for the raw
engine, and the reference-semantics host engine (hashgraph/graph.py)
for the full TpuHashgraph stack.
"""

from __future__ import annotations

import numpy as np
import pytest

from babble_tpu import crypto
from babble_tpu.gojson import Timestamp
from babble_tpu.hashgraph import Event, Hashgraph, InmemStore
from babble_tpu.hashgraph.tpu_graph import TpuHashgraph
from babble_tpu.ops.dag import synthetic_dag
from babble_tpu.ops.incremental import IncrementalEngine
from babble_tpu.ops.pipeline import run_pipeline


def test_pipelined_engine_matches_one_shot():
    """Interleaved appends (batch k+1 staged while pass k is in
    flight) + forced capacity AND chain-bucket regrowth == the
    one-shot full-DAG recompute, bit for bit."""
    n, e, bs = 8, 420, 48
    dag, _ = synthetic_dag(n, e, seed=11)
    # Tiny engine: event capacity 64 and chain buckets 8 force several
    # regrowths of every device carry mid-stream.
    eng = IncrementalEngine(n, capacity=64, block=64, k_capacity=8)
    pending = None
    k = 0
    while k < e:
        hi = min(k + bs, e)
        # Appends land BEFORE the previous pass is collected — they go
        # to the fresh staging list while the in-flight pass holds its
        # snapshot (the double-buffer seam under test).
        eng.append_batch(
            dag.self_parent[k:hi], dag.other_parent[k:hi],
            dag.creator[k:hi], dag.index[k:hi], dag.coin[k:hi],
            np.arange(k, hi))
        if pending is not None:
            eng.collect(pending)
        pending = eng.dispatch()
        k = hi
    if pending is not None:
        eng.collect(pending)
    # Drain to fixpoint: the last batch was staged during the final
    # in-flight pass.
    while True:
        pp = eng.dispatch()
        if pp is None:
            break
        eng.collect(pp)

    rounds, wit, wt, famous, rr, cts = map(
        np.asarray, run_pipeline(dag, engine="wavefront"))
    assert (eng.rounds[:e] == rounds).all()
    assert (eng.witness[:e] == wit).all()
    assert (eng.rr[:e] == rr).all()


def test_dispatch_collect_contract():
    """API misuse guards: double dispatch raises, collect of a stale
    pass raises, abandon restores the staged batch."""
    n = 4
    dag, _ = synthetic_dag(n, 64, seed=2)
    eng = IncrementalEngine(n, capacity=64, block=64, k_capacity=8)
    eng.append_batch(dag.self_parent[:32], dag.other_parent[:32],
                     dag.creator[:32], dag.index[:32], dag.coin[:32],
                     np.arange(32))
    pp = eng.dispatch()
    assert pp is not None and eng.inflight
    with pytest.raises(RuntimeError):
        eng.dispatch()
    eng.abandon(pp)
    assert not eng.inflight
    assert eng.backlog() == 32  # batch restored to staging
    with pytest.raises(RuntimeError):
        eng.collect(pp)  # abandoned pass is no longer in flight
    # The restored batch reruns cleanly.
    delta = eng.run()
    assert len(delta.new_rounds) == 32
    eng.close()


def _signed_gossip_events(n_peers, n_events, seed=13):
    """Random-gossip stream of REAL signed events (the shape the node
    runtime produces) plus the participant map."""
    rng = np.random.default_rng(seed)
    keys = [crypto.key_from_seed(5000 + i) for i in range(n_peers)]
    pubs = [crypto.pub_key_bytes(k) for k in keys]
    participants = {"0x" + p.hex().upper(): i for i, p in enumerate(pubs)}
    clock = 1_700_000_000_000_000_000
    heads = [""] * n_peers
    seqs = [-1] * n_peers
    events = []
    creators = np.concatenate([
        np.arange(n_peers),
        rng.integers(0, n_peers, size=n_events - n_peers)])
    others = rng.integers(1, n_peers, size=n_events)
    for i in range(n_events):
        c = int(creators[i])
        op = heads[(c + int(others[i])) % n_peers] if i >= n_peers else ""
        clock += 1_000_000
        seqs[c] += 1
        ev = Event.new([b"tx%d" % i], [heads[c], op], pubs[c], seqs[c],
                       timestamp=Timestamp(clock))
        ev.sign(keys[c])
        heads[c] = ev.hex()
        events.append(ev)
    return events, participants


def test_async_tpu_graph_matches_host_oracle():
    """Byte-identical consensus order vs the host oracle
    (hashgraph/graph.py) with the async pipeline driven the way the
    node's consensus worker drives it: insert a chunk, dispatch,
    insert the next chunk while the pass is in flight, collect. The
    tiny engine capacity forces regrowth across dispatch boundaries."""
    events, participants = _signed_gossip_events(4, 360)

    host = Hashgraph(participants, InmemStore(participants, 100000))
    for ev in events:
        host.insert_event(ev, True)
    host.run_consensus()

    tpu = TpuHashgraph(participants, InmemStore(participants, 100000),
                       capacity=64, block=64, k_capacity=8)
    pending = None
    cs = 60
    for lo in range(0, len(events), cs):
        for ev in events[lo:lo + cs]:
            tpu.insert_event(ev, True)
        if pending is not None:
            tpu.collect_consensus(pending)
        pending = tpu.dispatch_consensus()
    tpu.collect_consensus(pending)
    while True:
        pending = tpu.dispatch_consensus()
        if pending is None:
            break
        tpu.collect_consensus(pending)

    # THE acceptance check: identical consensus order, byte for byte.
    assert tpu.consensus_events() == host.consensus_events()
    # And identical per-event round/round-received on the full stream.
    for ev in events:
        h = ev.hex()
        assert tpu.round(h) == host.round(h)
        assert tpu.round_received(h) == host.round_received(h)
    tpu.engine.close()
