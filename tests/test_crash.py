"""Kill -9 crash-durability suite (ISSUE 4 acceptance).

Every test here runs REAL `babble_tpu.cli run` subprocesses over TCP
with FileStores and journal app proxies (tests/crash_harness.py), so a
SIGKILL is a genuine process death: no atexit, no flush, the sqlite
transaction torn at whatever instruction the kernel caught it.

Targeted tests pin the two hardest crash points exactly via the node's
seeded self-kill hooks (BABBLE_CRASH_AFTER_COMMITS / _AFTER_SYNCS):
mid-commit (app delivered, durable marker not yet advanced — restart
must NOT double-deliver) and mid-gossip (sync batch durable, consensus
for it not yet run — restart must replay to the survivors' exact
order). The soak drives seeded random SIGKILLs on top.

All slow-marked (subprocess testnets); CI's crash-smoke job runs them."""

from __future__ import annotations

import time

import pytest

from crash_harness import CrashTestnet, run_soak

pytestmark = pytest.mark.slow


def _cycle_victim(net, victim, env_extra, target_extra=2, timeout=240.0):
    """Start all nodes (victim with the self-kill env), wait for the
    victim to die at its crash point, advance the survivors, restart
    the victim with --bootstrap, and reconverge everyone."""
    for node in net.nodes:
        if node is victim:
            node.start(env_extra=env_extra)
        else:
            node.start()
    net.wait_up([n for n in net.nodes if n is not victim])

    # Feed traffic until the victim's crash point fires.
    deadline = time.monotonic() + timeout
    while victim.alive():
        assert time.monotonic() < deadline, "crash point never fired"
        try:
            victim.submit(f"trigger tx {net._tx_seq}".encode())
            net._tx_seq += 1
        except Exception:  # noqa: BLE001
            pass
        time.sleep(0.02)
    victim.wait_dead()

    survivors = [n for n in net.nodes if n is not victim]
    net.bombard_until(target_round=net.max_round() + target_extra,
                      timeout=timeout, require=survivors)

    victim.start()  # --bootstrap implied: store.db exists
    net.wait_up([victim])
    net.bombard_until(target_round=net.max_round() + 1, timeout=timeout)


def test_kill9_mid_commit(tmp_path):
    """SIGKILL between app delivery and the durable delivered marker:
    the restart re-emits the unmarked block and the journal dedupe must
    swallow it — zero duplicate deliveries, byte-identical order."""
    net = CrashTestnet(4, str(tmp_path), seed=404)
    victim = net.nodes[1]
    try:
        _cycle_victim(net, victim,
                      {"BABBLE_CRASH_AFTER_COMMITS": "2"})
    finally:
        net.shutdown_all()
    result = net.assert_invariants()
    assert result["deliveries"] > 0
    assert victim.kills == 0  # it killed ITSELF at the crash point


def test_kill9_mid_gossip(tmp_path):
    """SIGKILL right after a sync batch committed durably, before any
    consensus pass decided it: bootstrap must replay the torn tail and
    reach the survivors' exact block order."""
    net = CrashTestnet(4, str(tmp_path), seed=405)
    victim = net.nodes[2]
    try:
        _cycle_victim(net, victim,
                      {"BABBLE_CRASH_AFTER_SYNCS": "4"})
    finally:
        net.shutdown_all()
    net.assert_invariants()


def test_kill9_restart_beyond_sync_limit_fast_forwards(tmp_path):
    """A restarted node that fell beyond sync_limit while dead must
    catch up through the fast-forward path against its reloaded store
    and still satisfy every durability invariant."""
    net = CrashTestnet(4, str(tmp_path), seed=406,
                       extra_args=["--sync_limit", "30"])
    victim = net.nodes[0]
    try:
        net.start_all()
        net.wait_up()
        net.bombard_until(target_round=2, timeout=240.0)
        victim.kill9()
        survivors = [n for n in net.nodes if n is not victim]
        # Push the survivors far enough that the victim trails by more
        # than sync_limit events when it comes back.
        net.bombard_until(target_round=net.max_round() + 6,
                          timeout=300.0, require=survivors)
        victim.start()
        net.wait_up([victim])
        net.bombard_until(target_round=net.max_round() + 2, timeout=300.0)
        stats = victim.stats()
        assert int(stats["fast_forwards"]) >= 1, (
            "victim caught up without fast-forwarding; raise the gap")
    finally:
        net.shutdown_all()
    net.assert_invariants()


def test_crash_soak(tmp_path):
    """The acceptance soak: seeded random SIGKILLs mid-traffic across
    two kill/restart cycles, then byte-identical block order and
    exactly-once delivery audits across every node."""
    result = run_soak(str(tmp_path), n=4, seed=31337, kills=2)
    assert result["blocks"] > 0
    assert result["deliveries"] > 0
