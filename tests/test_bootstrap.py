"""Crash-recovery: run a FileStore-backed testnet, shut it down, reload
every node from its database, continue gossiping, and cross-check old vs
new consensus — the TestBootstrapAllNodes analog (reference
node/node_test.go:477-505)."""

from __future__ import annotations

import time

from babble_tpu.hashgraph import FileStore
from babble_tpu.net import InmemTransport
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.node import Node
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.proxy import InmemAppProxy

from test_node import check_gossip, make_keyed_peers, run_gossip

CACHE = 10000


def make_file_nodes(n, tmp_path, fresh=True, engine="host"):
    transports = [InmemTransport(f"addr{i}", timeout=2.0) for i in range(n)]
    connect_all(transports)
    entries = make_keyed_peers(n, addr_fn=lambda i: f"addr{i}")
    by_addr = {t.local_addr(): t for t in transports}
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}

    nodes = []
    for i, (key, peer) in enumerate(entries):
        path = str(tmp_path / f"node{i}.db")
        if fresh:
            store = FileStore(participants, CACHE, path)
        else:
            store = FileStore.load(CACHE, path)
        conf = fast_config(heartbeat=0.01)
        conf.engine = engine
        node = Node(conf, i, key, peers, store, by_addr[peer.net_addr],
                    InmemAppProxy())
        node.init(bootstrap=not fresh)
        nodes.append(node)
    return nodes


def test_bootstrap_all_nodes(tmp_path):
    nodes = make_file_nodes(4, tmp_path, fresh=True)
    run_gossip(nodes, target_round=5)
    check_gossip(nodes)
    first_events = {n.id: n.core.get_consensus_events() for n in nodes}
    first_rounds = {n.id: n.core.get_last_consensus_round_index() for n in nodes}
    assert all(r is not None and r >= 5 for r in first_rounds.values())

    # recycle: reload every node from its database and keep going
    nodes2 = make_file_nodes(4, tmp_path, fresh=False)
    # bootstrap recovered the consensus state
    for n in nodes2:
        recovered = n.core.get_consensus_events()
        prior = first_events[n.id]
        m = min(len(recovered), len(prior))
        assert m > 0 and recovered[:m] == prior[:m], (
            f"node {n.id} lost consensus history on reload"
        )
        assert n.core.head != "" and n.core.seq >= 0

    target = max(first_rounds.values()) + 3
    run_gossip(nodes2, target_round=target)
    check_gossip(nodes2)
    # the continued history extends the pre-restart history
    for n in nodes2:
        cont = n.core.get_consensus_events()
        prior = first_events[n.id]
        m = min(len(cont), len(prior))
        assert cont[:m] == prior[:m]


def test_bootstrap_all_nodes_tpu_engine(tmp_path):
    """Crash-recovery with the device engine deciding consensus: the
    FileStore topological replay drives TpuHashgraph.bootstrap (inserts
    + one engine run with commit callbacks suppressed), and the revived
    testnet continues from the recovered state."""
    from babble_tpu.hashgraph.tpu_graph import TpuHashgraph

    nodes = make_file_nodes(3, tmp_path, fresh=True, engine="tpu")
    for node in nodes:
        assert isinstance(node.core.hg, TpuHashgraph)
    run_gossip(nodes, target_round=3, timeout=120.0)
    check_gossip(nodes)
    first_events = {n.id: n.core.get_consensus_events() for n in nodes}
    first_rounds = {
        n.id: n.core.get_last_consensus_round_index() for n in nodes}

    # Replay can legitimately decide MORE than the pre-shutdown snapshot
    # (a tip event inserted after the last run_consensus gets decided by
    # the bootstrap recompute), so the recovered state is compared as a
    # prefix, like the host analog above.
    nodes = make_file_nodes(3, tmp_path, fresh=False, engine="tpu")
    for node in nodes:
        assert isinstance(node.core.hg, TpuHashgraph)
        assert (node.core.get_last_consensus_round_index()
                >= first_rounds[node.id])
        recovered = node.core.get_consensus_events()
        assert recovered[: len(first_events[node.id])] == first_events[node.id]
    run_gossip(nodes, target_round=max(first_rounds.values()) + 2,
               timeout=120.0)
    check_gossip(nodes)
    for node in nodes:
        assert node.core.get_consensus_events()[: len(first_events[node.id])] \
            == first_events[node.id]
