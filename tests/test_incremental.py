"""Incremental device engine + TpuHashgraph integration tests.

Three layers of parity, mirroring the reference's oracle strategy
(hashgraph_test.go fixtures -> core_test.go playbooks -> node_test.go
checkGossip):

1. IncrementalEngine fed in batches must equal the one-shot full
   pipeline bit-for-bit (rounds, witnesses, fame, round-received,
   consensus timestamps) across capacity/chain-bucket growth.
2. TpuHashgraph driven event-by-event must equal the incremental host
   engine on the reference fixture graphs: same rounds, witness sets,
   fame trileans, consensus order, and block hashes.
3. The live gossip runtime (reference node_test.go:396-420) must
   converge with the device engine deciding consensus.
"""

from __future__ import annotations

import numpy as np
import pytest

from babble_tpu.common import StoreError
from babble_tpu.hashgraph import InmemStore
from babble_tpu.hashgraph.event import Event
from babble_tpu.hashgraph.root import Root
from babble_tpu.hashgraph.round_info import Trilean
from babble_tpu.hashgraph.tpu_graph import TpuHashgraph
from babble_tpu.ops.dag import synthetic_dag
from babble_tpu.ops.incremental import CTS_SENTINEL, IncrementalEngine
from babble_tpu.ops.pipeline import run_pipeline

from fixtures import (
    build_consensus_graph,
    build_funky_graph,
    build_round_graph,
)
from test_node import check_gossip, make_nodes, run_gossip

CACHE = 10000


def make_tpu_twin(build):
    """Host graph with consensus run + a TpuHashgraph fed the same
    fixture stream (consensus run once at the end)."""
    h, b = build()
    h.divide_rounds()
    h.decide_fame()
    h.find_order()
    participants = b.participants()
    t = TpuHashgraph(participants, InmemStore(participants, CACHE),
                     capacity=64, block=64)
    for ev in b.ordered_events:
        t.insert_event(ev, True)
    t.run_consensus()
    return h, b, t


@pytest.mark.parametrize(
    "n,e,bs", [(8, 300, 37), (5, 97, 10)], ids=["n8", "n5"]
)
def test_engine_matches_full_pipeline(n, e, bs):
    """Batched ingest with run() between batches == one-shot recompute,
    across capacity doubling and chain-bucket growth."""
    dag, _ = synthetic_dag(n, e, seed=3)
    eng = IncrementalEngine(n, capacity=64, block=64, k_capacity=8)
    k = 0
    while k < e:
        hi = min(k + bs, e)
        eng.append_batch(
            dag.self_parent[k:hi], dag.other_parent[k:hi],
            dag.creator[k:hi], dag.index[k:hi], dag.coin[k:hi],
            np.arange(k, hi))
        eng.run()
        k = hi

    rounds, wit, wt, famous, rr, cts = map(
        np.asarray, run_pipeline(dag, engine="wavefront"))
    assert (eng.rounds[:e] == rounds).all()
    assert (eng.witness[:e] == wit).all()
    assert (eng.rr[:e] == rr).all()
    wt_abs = eng.witness_table()
    rt = wt_abs.shape[0]
    assert (wt_abs == wt[:rt]).all()
    assert (wt[rt:] == -1).all()
    assert (eng.famous == famous[:rt]).all()
    dec = rr >= 0
    # pipeline cts are ranks into dag.ts_values == arange(e); -1 = zero time
    cts_ns = np.where(cts < 0, CTS_SENTINEL, cts.astype(np.int64))
    assert (eng.cts_ns[:e][dec] == cts_ns[dec]).all()


@pytest.mark.parametrize(
    "build,every",
    [(build_round_graph, 4), (build_consensus_graph, 7),
     (build_funky_graph, 3)],
    ids=["round", "consensus", "funky"],
)
def test_tpu_graph_matches_host(build, every):
    """TpuHashgraph with interleaved run_consensus calls reproduces the
    host engine's rounds, witness sets, fame, consensus order, and
    blocks on the reference fixture graphs."""
    h, b = build()
    h.divide_rounds()
    h.decide_fame()
    h.find_order()

    participants = b.participants()
    t = TpuHashgraph(participants, InmemStore(participants, CACHE),
                     capacity=64, block=64)
    for k, ev in enumerate(b.ordered_events):
        t.insert_event(ev, True)
        if (k + 1) % every == 0:
            t.run_consensus()
    t.run_consensus()

    for ev in b.ordered_events:
        x = ev.hex()
        assert t.round(x) == h.round(x), b.get_name(x)
        assert t.witness(x) == h.witness(x), b.get_name(x)
        assert t.round_received(x) == h.round_received(x), b.get_name(x)
    for r in range(h.store.last_round() + 1):
        assert set(t.store.round_witnesses(r)) == set(
            h.store.round_witnesses(r)), f"round {r}"
        hri = h.store.get_round(r)
        tri = t.store.get_round(r)
        for w in hri.witnesses():
            assert tri.events[w].famous == hri.events[w].famous, (
                f"fame mismatch {b.get_name(w)} round {r}")
    assert t.consensus_events() == h.consensus_events()
    assert t.last_consensus_round == h.last_consensus_round
    assert t.pending_loaded_events == h.pending_loaded_events
    assert t.consensus_transactions == h.consensus_transactions
    assert set(t.undetermined_events) == set(h.undetermined_events)
    for r in range(h.store.last_round() + 1):
        try:
            hb = h.store.get_block(r)
        except Exception:
            continue
        tb = t.store.get_block(r)
        assert tb.hash() == hb.hash(), f"block {r}"


def test_tpu_graph_consensus_timestamps():
    """Consensus timestamps (median over famous-witness first
    descendants) must match the host engine exactly — they are the
    second consensus sort key."""
    h, b, t = make_tpu_twin(build_consensus_graph)
    for x in h.consensus_events():
        he = h.store.get_event(x)
        te = t.store.get_event(x)
        assert te.consensus_timestamp.ns == he.consensus_timestamp.ns, (
            b.get_name(x))


def test_gossip_tpu_engine():
    """4-node gossip over the inmem transport with the device engine
    deciding consensus — reference node_test.go:396-407 with the
    JaxStore-sibling integration (SURVEY §7 step 3)."""
    nodes = make_nodes(4, "inmem", engine="tpu")
    for node in nodes:
        assert isinstance(node.core.hg, TpuHashgraph)
    # Generous budget: the engine jit-compiles several bucketed window
    # shapes on first use, and under a full-suite run those compiles
    # contend with other tests' caches (the isolated run sits near 110s).
    run_gossip(nodes, target_round=5, timeout=300.0)
    check_gossip(nodes)


def test_tpu_graph_get_frame_matches_host():
    """GetFrame (the fast-sync snapshot, reference hashgraph.go:900-1002)
    served from device-backed state must equal the host engine's frame:
    same roots and the same events in the same (topological) order —
    the order matters because frames are replayed in order during
    fast-sync."""
    h, b, t = make_tpu_twin(build_consensus_graph)

    hf = h.get_frame()
    tf = t.get_frame()
    assert [e.hex() for e in tf.events] == [e.hex() for e in hf.events]
    assert set(tf.roots) == set(hf.roots)
    for pk, hr in hf.roots.items():
        tr = tf.roots[pk]
        assert (tr.x, tr.y, tr.index, tr.round, tr.others) == (
            hr.x, hr.y, hr.index, hr.round, hr.others), pk


def test_run_unlocked_appends_interleave():
    """The live node releases the core lock around the device-result
    wait (node/node.py _core_unlocked), so appends can land MID-run.
    The pass must operate on its snapshot — neither corrupting results
    for the dispatched batch nor losing the interleaved events. Final
    state must equal a serial engine fed the same stream."""
    import contextlib

    from babble_tpu.ops.dag import synthetic_dag as sdag

    n, e, bs = 8, 400, 57
    dag, _ = sdag(n, e, seed=9)
    batches = [(k, min(k + bs, e)) for k in range(0, e, bs)]

    def feed(g, k, hi):
        g.append_batch(
            dag.self_parent[k:hi], dag.other_parent[k:hi],
            dag.creator[k:hi], dag.index[k:hi], dag.coin[k:hi],
            np.arange(k, hi))

    ref = IncrementalEngine(n, capacity=64, block=64, k_capacity=8)
    for k, hi in batches:
        feed(ref, k, hi)
        ref.run()

    eng = IncrementalEngine(n, capacity=64, block=64, k_capacity=8)
    state = {"next": 1}

    @contextlib.contextmanager
    def interleave():
        # Fires exactly where the node's lock release does: during the
        # blocking pull. Inject the next batch right there.
        if state["next"] < len(batches):
            k, hi = batches[state["next"]]
            state["next"] += 1
            feed(eng, k, hi)
        yield

    feed(eng, *batches[0])
    for _ in range(3 * len(batches)):
        eng.run(unlocked=interleave)
        if state["next"] >= len(batches):
            break
    eng.run()  # drain whatever the last interleave injected

    assert (eng.rounds[:e] == ref.rounds[:e]).all()
    assert (eng.witness[:e] == ref.witness[:e]).all()
    assert (eng.rr[:e] == ref.rr[:e]).all()
    assert (eng.cts_ns[:e] == ref.cts_ns[:e]).all()
    assert (eng.famous == ref.famous).all()
    assert eng.undecided_rounds == ref.undecided_rounds


def test_run_retries_after_transient_failure():
    """A pass that dies mid-flight (tunnel drop, preemption) must not
    orphan its batch: the snapshot is restored, the node's consensus
    worker retries, and the retry produces the same results as a
    never-failed engine."""
    import contextlib

    from babble_tpu.ops.dag import synthetic_dag as sdag

    n, e = 8, 200
    dag, _ = sdag(n, e, seed=4)

    def feed(g, k, hi):
        g.append_batch(
            dag.self_parent[k:hi], dag.other_parent[k:hi],
            dag.creator[k:hi], dag.index[k:hi], dag.coin[k:hi],
            np.arange(k, hi))

    ref = IncrementalEngine(n, capacity=64, block=64, k_capacity=8)
    feed(ref, 0, 120)
    ref.run()
    feed(ref, 120, e)
    ref.run()

    eng = IncrementalEngine(n, capacity=64, block=64, k_capacity=8)
    feed(eng, 0, 120)

    @contextlib.contextmanager
    def tunnel_drop():
        raise RuntimeError("tunnel dropped")
        yield  # pragma: no cover

    with pytest.raises(RuntimeError):
        eng.run(unlocked=tunnel_drop)
    eng.run()  # retry re-mirrors the restored batch
    feed(eng, 120, e)
    eng.run()

    assert (eng.rounds[:e] == ref.rounds[:e]).all()
    assert (eng.witness[:e] == ref.witness[:e]).all()
    assert (eng.rr[:e] == ref.rr[:e]).all()
    assert (eng.famous == ref.famous).all()
    assert eng.undecided_rounds == ref.undecided_rounds


# ---------------------------------------------------------------- reset


def _assert_consensus_parity(h, t, hexes, label=lambda x: x):
    assert t.store.last_round() == h.store.last_round()
    for x in hexes:
        assert t.round(x) == h.round(x), label(x)
        assert t.witness(x) == h.witness(x), label(x)
        assert t.round_received(x) == h.round_received(x), label(x)
    for r in range(h.store.last_round() + 1):
        assert set(t.store.round_witnesses(r)) == set(
            h.store.round_witnesses(r)), f"round {r}"
        try:
            hri = h.store.get_round(r)
        except StoreError:
            # Post-reset stores start at the roots' round; both engines
            # must agree on which rounds exist at all.
            with pytest.raises(StoreError):
                t.store.get_round(r)
            continue
        tri = t.store.get_round(r)
        for w in hri.witnesses():
            assert tri.events[w].famous == hri.events[w].famous, (
                f"fame mismatch round {r}")
    assert t.consensus_events() == h.consensus_events()
    assert t.last_consensus_round == h.last_consensus_round


def test_tpu_reset():
    """Manual-roots reset then tail replay on the device engine — the
    mirror of test_hashgraph.py::test_reset (reference
    hashgraph_test.go:1144): Roots with offset chain bases (index=4,
    round=2) and an Others entry, followed by continued consensus over
    the replayed tail, bit-identical to the host engine."""
    h, b, t = make_tpu_twin(build_consensus_graph)
    i = b.index
    evs = ["g1", "g0", "g2", "g10", "g21", "o02", "g02", "h1", "h0", "h2"]

    def mk_roots():
        return {
            h.reverse_participants[0]: Root(
                x=i["f02b"], y=i["g1"], index=4, round=2,
                others={i["o02"]: i["f21"]},
            ),
            h.reverse_participants[1]: Root(
                x=i["f10"], y=i["f02b"], index=4, round=2),
            h.reverse_participants[2]: Root(
                x=i["f21"], y=i["g1"], index=4, round=2),
        }

    def backups(g):
        out = []
        for name in evs:
            ev = g.store.get_event(i[name])
            out.append(Event(ev.body, r=ev.r, s=ev.s))
        return out

    hb, tb = backups(h), backups(t)
    h.reset(mk_roots())
    t.reset(mk_roots())
    for eh, et in zip(hb, tb):
        h.insert_event(eh, False)
        t.insert_event(et, False)
    assert h.known() == {0: 8, 1: 7, 2: 7}
    assert t.known() == h.known()

    h.divide_rounds()
    h.decide_fame()
    h.find_order()
    t.run_consensus()
    _assert_consensus_parity(h, t, [i[name] for name in evs], b.get_name)


def test_tpu_reset_from_frame():
    """get_frame -> reset -> full frame replay on the device engine
    (reference hashgraph_test.go:1302): known(), rounds, witnesses,
    fame trileans, and the re-derived last_consensus_round must all
    match the host engine performing the same reset."""
    h, b, t = make_tpu_twin(build_consensus_graph)
    hf = h.get_frame()
    tf = t.get_frame()

    h.reset(hf.roots)
    t.reset(tf.roots)
    for ev in hf.events:
        h.insert_event(Event(ev.body, r=ev.r, s=ev.s), False)
    for ev in tf.events:
        t.insert_event(Event(ev.body, r=ev.r, s=ev.s), False)

    assert h.known() == {0: 8, 1: 7, 2: 7}
    assert t.known() == h.known()

    h.divide_rounds()
    h.decide_fame()
    h.find_order()
    t.run_consensus()
    assert h.last_consensus_round == 1
    _assert_consensus_parity(
        h, t, [e.hex() for e in hf.events], b.get_name)


def test_append_batch_vectorized_matches_serial():
    """The vectorized append_batch (one slice assignment per staging
    column) must leave the engine bit-identical to per-event appends —
    including interleaved-creator batches, capacity doubling, and
    chain-bucket growth — and reject the same invalid batches."""
    dag, _ = synthetic_dag(8, 400, seed=3)
    ts = np.arange(400, dtype=np.int64) * 7 + 100
    serial = IncrementalEngine(8, capacity=64, block=64, k_capacity=8)
    batched = IncrementalEngine(8, capacity=64, block=64, k_capacity=8)
    for k in range(400):
        serial.append(int(dag.self_parent[k]), int(dag.other_parent[k]),
                      int(dag.creator[k]), int(dag.index[k]),
                      bool(dag.coin[k]), int(ts[k]))
    lo = 0
    for size in (1, 3, 17, 64, 5, 127, 400):
        hi = min(400, lo + size)
        first = batched.append_batch(
            dag.self_parent[lo:hi], dag.other_parent[lo:hi],
            dag.creator[lo:hi], dag.index[lo:hi], dag.coin[lo:hi],
            ts[lo:hi])
        assert first == lo
        lo = hi
    for name in ("self_parent", "other_parent", "creator", "index",
                 "coin", "root_base", "ts_ns", "chain", "chain_len",
                 "rounds", "witness", "rr", "cts_ns"):
        assert np.array_equal(getattr(serial, name),
                              getattr(batched, name)), name
    assert serial.e == batched.e
    assert serial._new_since_run == batched._new_since_run

    with pytest.raises(ValueError):
        batched.append_batch(
            np.array([-1, 5]), np.array([-1, -1]), np.array([0, 0]),
            np.array([999, 1000]), np.array([0, 0]), np.array([1, 2]))

    serial.run()
    batched.run()
    assert np.array_equal(serial.rounds[:serial.e],
                          batched.rounds[:batched.e])
    assert np.array_equal(serial.rr[:serial.e], batched.rr[:batched.e])


def test_tpu_insert_wire_batch_matches_serial_inserts():
    """Device-direct ingest seam: TpuHashgraph.insert_wire_batch (host
    checks per event, ONE vectorized engine append) must equal the
    serial insert_event loop — engine state, store contents, and the
    consensus it then decides."""
    h, b = build_consensus_graph()
    participants = b.participants()

    serial = TpuHashgraph(participants, InmemStore(participants, CACHE),
                          capacity=64, block=64)
    batched = TpuHashgraph(participants, InmemStore(participants, CACHE),
                           capacity=64, block=64)
    evs = b.ordered_events
    for ev in evs:
        serial.insert_event(Event(ev.body, r=ev.r, s=ev.s), True)
    # two chunks, split mid-stream, cloned events
    mid = len(evs) // 2
    batched.insert_wire_batch(
        [Event(e.body, r=e.r, s=e.s) for e in evs[:mid]])
    batched.insert_wire_batch(
        [Event(e.body, r=e.r, s=e.s) for e in evs[mid:]])

    assert serial.known() == batched.known()
    assert serial.undetermined_events == batched.undetermined_events
    eng_s, eng_b = serial.engine, batched.engine
    for name in ("self_parent", "other_parent", "creator", "index",
                 "coin", "ts_ns", "chain", "chain_len"):
        assert np.array_equal(getattr(eng_s, name),
                              getattr(eng_b, name)), name
    serial.run_consensus()
    batched.run_consensus()
    assert serial.store.consensus_events() == \
        batched.store.consensus_events()
    assert serial.last_consensus_round == batched.last_consensus_round
