"""Go-JSON encoding vectors. Expected strings derived from the behavior of
Go's encoding/json (json.Encoder with default HTML escaping), which is
what the reference hashes to name events (reference
hashgraph/event.go:30-54,155-188)."""

from babble_tpu.gojson import (
    BigInt,
    GoStruct,
    Timestamp,
    ZERO_TIME,
    marshal,
)


class Inner(GoStruct):
    go_fields = (("A", "a"), ("B", "b"))

    def __init__(self, a, b):
        self.a = a
        self.b = b


def test_primitives():
    assert marshal(Inner(1, "x")) == b'{"A":1,"B":"x"}\n'
    assert marshal(Inner(None, [])) == b'{"A":null,"B":[]}\n'
    assert marshal(Inner(True, False)) == b'{"A":true,"B":false}\n'


def test_bytes_base64():
    assert marshal(Inner(b"hi", None)) == b'{"A":"aGk=","B":null}\n'
    # [][]byte{} -> [], nil -> null
    assert marshal(Inner([b"a", b"bc"], None)) == b'{"A":["YQ==","YmM="],"B":null}\n'


def test_html_escaping():
    assert marshal(Inner("<&>", None)) == b'{"A":"\\u003c\\u0026\\u003e","B":null}\n'


def test_bigint():
    big = BigInt(2**300 + 7)
    out = marshal(Inner(big, 0))
    assert out == b'{"A":%d,"B":0}\n' % (2**300 + 7)


def test_map_key_sorting():
    # Go sorts map keys by string form: "10" < "2" lexicographically.
    assert marshal(Inner({10: "x", 2: "y"}, None)) == b'{"A":{"10":"x","2":"y"},"B":null}\n'


def test_timestamp_rfc3339nano():
    # 2021-09-13T12:26:40.000000123Z
    ts = Timestamp(1631536000 * 1_000_000_000 + 123)
    assert ts.rfc3339nano() == "2021-09-13T12:26:40.000000123Z"
    # trailing zeros trimmed
    ts2 = Timestamp(1631536000 * 1_000_000_000 + 500_000_000)
    assert ts2.rfc3339nano() == "2021-09-13T12:26:40.5Z"
    # no fraction
    ts3 = Timestamp(1631536000 * 1_000_000_000)
    assert ts3.rfc3339nano() == "2021-09-13T12:26:40Z"


def test_timestamp_zero_time():
    assert ZERO_TIME.rfc3339nano() == "0001-01-01T00:00:00Z"


def test_timestamp_parse_roundtrip():
    for s in [
        "2021-09-13T12:26:40.000000123Z",
        "2021-09-13T12:26:40.5Z",
        "2021-09-13T12:26:40Z",
        "0001-01-01T00:00:00Z",
    ]:
        assert Timestamp.parse(s).rfc3339nano() == s
    # offset form normalizes to Z
    assert Timestamp.parse("2021-09-13T14:26:40+02:00").rfc3339nano() == "2021-09-13T12:26:40Z"
