"""Ingress armor (docs/ingress.md): batched submit, admission control,
quotas, commit subscriptions, and the overload contract — shed at the
front door, never drop on the commit path."""

from __future__ import annotations

import http.client
import json
import time
import urllib.error
import urllib.request

import pytest

from babble_tpu.hashgraph import Block, FileStore, InmemStore
from babble_tpu.net import InmemTransport
from babble_tpu.net.faulty_transport import FaultyTransport
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.node import Node
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.proxy import InmemAppProxy
from babble_tpu.proxy.file_app_proxy import FileAppProxy
from babble_tpu.service import Service
from babble_tpu.service.ingress import (
    AdmissionController,
    ClientQuotas,
    CommitSubscriptions,
    TokenBucket,
    decode_tx_batch,
    encode_tx_batch,
    tx_digest,
)
from babble_tpu.telemetry import promtext

from test_node import make_keyed_peers

CACHE = 10000


# ---------------------------------------------------------------- unit


def test_tx_batch_roundtrip():
    txs = [b"a", b"bb" * 100, b"\x00\xff" * 7]
    data = encode_tx_batch(txs)
    assert decode_tx_batch(data, max_tx_bytes=1 << 20) == txs


def test_tx_batch_rejects_malformed():
    txs = [b"one", b"two"]
    good = encode_tx_batch(txs)
    for bad, why in [
        (b"", "too short"),
        (b"XXXX" + good[4:], "bad magic"),
        (good[:-1], "truncated payload"),
        (good + b"x", "trailing bytes"),
        (encode_tx_batch([b""]), "empty tx"),
    ]:
        with pytest.raises(ValueError):
            decode_tx_batch(bad, max_tx_bytes=1 << 20)
    with pytest.raises(ValueError):
        decode_tx_batch(encode_tx_batch([b"x" * 100]), max_tx_bytes=10)
    with pytest.raises(ValueError):
        decode_tx_batch(encode_tx_batch([b"x"] * 5), max_tx_bytes=1 << 20,
                        max_txs=4)


def test_token_bucket_refill_and_retry():
    b = TokenBucket(rate=10.0, burst=5.0, now=100.0)
    assert b.grant(5, 100.0) == 5
    assert b.grant(1, 100.0) == 0
    # refill: 0.2s at 10/s = 2 tokens
    assert b.grant(5, 100.2) == 2
    assert b.retry_after() > 0.0
    # a full burst is restored after burst/rate seconds
    assert b.grant(5, 200.0) == 5


def test_client_quotas_partial_grant_and_eviction():
    q = ClientQuotas(rate=10.0, burst=4.0, max_clients=3)
    granted, retry = q.grant("a", 6, now=0.0)
    assert granted == 4 and retry > 0.0
    # disabled quotas grant everything
    q0 = ClientQuotas(rate=0.0)
    assert not q0.enabled
    assert q0.grant("anyone", 1000, now=0.0) == (1000, 0.0)
    # bounded table: a 4th client evicts the least-recently-seen
    for c in ("b", "c", "d"):
        q.grant(c, 1, now=1.0)
    assert len(q._buckets) == 3
    assert "a" not in q._buckets
    # auto burst floors at 64
    assert ClientQuotas(rate=1.0).burst == 64.0


def test_admission_controller_codel_law():
    c = AdmissionController(target=0.1, interval=0.5)
    # below target: always admit, never arm
    assert c.admit(0.05, now=0.0)
    assert not c.state()["shedding"]
    # above target arms the interval; sheds only after a full one
    assert c.admit(0.2, now=1.0)
    assert c.admit(0.2, now=1.4)
    assert not c.admit(0.2, now=1.6)
    assert c.state()["shedding"]
    # while shedding the ramp spaces rejections, admitting between:
    # the next shed comes a full interval after the first (count=1),
    # then interval/sqrt(count) after that
    assert c.admit(0.2, now=1.7)
    assert not c.admit(0.2, now=2.11)
    assert not c.admit(0.2, now=2.11 + 0.5 / (2 ** 0.5) + 0.01)
    # first sample back under target exits and counts the episode
    assert c.admit(0.05, now=3.0)
    st = c.state()
    assert not st["shedding"] and st["episodes"] == 1


def test_commit_subscriptions_registry():
    s = CommitSubscriptions(max_waiters=2, recent_cap=4)
    w = s.register("d1")
    assert w is not None and not w.event.is_set()
    s.resolve("d1", {"round": 7})
    assert w.event.is_set() and w.result == {"round": 7}
    assert s.waiter_count() == 0
    # resolved digests answer from the ring without parking
    w2 = s.register("d1")
    assert w2.event.is_set() and w2.result == {"round": 7}
    # the waiter cap sheds instead of parking unboundedly
    assert s.register("a") is not None
    assert s.register("b") is not None
    assert s.register("c") is None
    # the ring is bounded
    for i in range(10):
        s.resolve(f"r{i}", {"round": i})
    assert len(s._recent) <= 4


def test_file_app_proxy_coalesced_fsync(tmp_path):
    """sync="batch" (the --journal default) fsyncs once per flush()
    call — the node calls it per drained commit burst — instead of
    once per block; sync="always" keeps the per-block policy."""
    p = FileAppProxy(str(tmp_path / "batch.jsonl"))
    for r in range(5):
        p.commit_block(Block(r, [b"tx %d" % r]))
    assert p.fsync_count == 0
    p.flush()
    assert p.fsync_count == 1
    p.flush()  # clean: no extra fsync
    assert p.fsync_count == 1
    assert len(p.committed_transactions()) == 5
    p.close()

    pa = FileAppProxy(str(tmp_path / "always.jsonl"), sync="always")
    for r in range(3):
        pa.commit_block(Block(r, [b"t"]))
    assert pa.fsync_count == 3
    pa.close()


# ---------------------------------------------------------------- http


def make_ingress_nodes(n, heartbeat=0.01, stores=None, faults=None,
                       **conf_overrides):
    """An n-node inmem testnet with per-node conf overrides — the
    ingress plane's knobs live on Config, so tests build their own
    nodes instead of reusing make_nodes."""
    inner = [InmemTransport(f"addr{i}", timeout=2.0) for i in range(n)]
    connect_all(inner)
    if faults:
        trans = {t.local_addr(): FaultyTransport(t, seed=11, **faults)
                 for t in inner}
    else:
        trans = {t.local_addr(): t for t in inner}
    entries = make_keyed_peers(n, addr_fn=lambda i: f"addr{i}")
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = fast_config(heartbeat=heartbeat)
        for k, v in conf_overrides.items():
            setattr(conf, k, v)
        store = (stores[i](participants) if stores
                 else InmemStore(participants, CACHE))
        node = Node(conf, i, key, peers, store,
                    trans[peer.net_addr], InmemAppProxy())
        node.init()
        nodes.append(node)
    return nodes


def _post(url, data, headers=None, timeout=10):
    req = urllib.request.Request(url, data=data, method="POST",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), r.headers


def _wait_committed(nodes, txs, timeout=60.0):
    deadline = time.monotonic() + timeout
    want = set(txs)
    while time.monotonic() < deadline:
        if all(want <= set(n.core.get_consensus_transactions())
               for n in nodes):
            return
        time.sleep(0.1)
    missing = [len(want - set(n.core.get_consensus_transactions()))
               for n in nodes]
    raise AssertionError(f"txs not committed everywhere: missing {missing}")


def test_submit_batch_binary_and_json():
    """Both /submit/batch forms land txs in consensus, digests line up
    with sha256(tx), and /subscribe resolves once committed."""
    nodes = make_ingress_nodes(4)
    services = [Service("127.0.0.1:0", nd) for nd in nodes]
    for s in services:
        s.serve_async()
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        bin_txs = [b"bin tx %d" % i for i in range(20)]
        code, doc, _ = _post(f"http://{services[0].addr}/submit/batch",
                             encode_tx_batch(bin_txs))
        assert code == 200
        assert doc["submitted"] == 20
        assert doc["statuses"] == ["accepted"] * 20
        assert doc["digests"] == [tx_digest(t) for t in bin_txs]

        import base64
        json_txs = [b"json tx %d" % i for i in range(10)]
        body = json.dumps([base64.b64encode(t).decode()
                           for t in json_txs]).encode()
        code, doc, _ = _post(f"http://{services[1].addr}/submit/batch",
                             body)
        assert code == 200 and doc["submitted"] == 10

        # single /submit now returns the subscription digest too
        code, doc, _ = _post(f"http://{services[0].addr}/submit",
                             b"single tx")
        assert code == 200
        assert doc == {"submitted": len(b"single tx"),
                       "digest": tx_digest(b"single tx")}

        all_txs = bin_txs + json_txs + [b"single tx"]
        _wait_committed(nodes, all_txs)

        # /subscribe on a committed digest answers immediately from
        # the recent ring (long-poll form)
        d = tx_digest(bin_txs[0])
        with urllib.request.urlopen(
                f"http://{services[0].addr}/subscribe?tx={d}&timeout=5",
                timeout=10) as r:
            assert r.status == 200
            sub = json.loads(r.read())
        assert sub["tx"] == d and sub["round"] >= 0

        # SSE form: one `commit` event, then the stream closes
        req = urllib.request.Request(
            f"http://{services[0].addr}/subscribe?tx={d}&timeout=5",
            headers={"Accept": "text/event-stream"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.headers["Content-Type"] == "text/event-stream"
            stream = r.read().decode()
        assert "event: commit" in stream
        assert d in stream

        # unknown digest: 204 on long-poll timeout
        unknown = "0" * 64
        req = urllib.request.Request(
            f"http://{services[0].addr}/subscribe"
            f"?tx={unknown}&timeout=0.2")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 204

        # malformed digest: 400
        try:
            urllib.request.urlopen(
                f"http://{services[0].addr}/subscribe?tx=nope",
                timeout=5)
            raise AssertionError("bad digest accepted")
        except urllib.error.HTTPError as err:
            assert err.code == 400

        # malformed batches: 400, not a stack trace
        for bad in (encode_tx_batch([b"x"])[:-1], b"{}", b"[]"):
            try:
                _post(f"http://{services[0].addr}/submit/batch", bad)
                raise AssertionError("malformed batch accepted")
            except urllib.error.HTTPError as err:
                assert err.code == 400

        # /debug/ingress reflects the work
        with urllib.request.urlopen(
                f"http://{services[0].addr}/debug/ingress",
                timeout=5) as r:
            dbg = json.loads(r.read())
        assert dbg["admission"] is True
        assert dbg["admitted"] >= 21
        assert set(dbg["shed"]) == {"overload", "downstream",
                                    "intake_full", "subscribers"}
        assert "controller" in dbg and "intake" in dbg
    finally:
        for s in services:
            s.close()
        for nd in nodes:
            nd.shutdown()


def test_no_admission_kill_switch():
    """--no_admission restores the bare intake path byte-for-byte:
    the old /submit response shape, no ingress object, /subscribe
    answers 503."""
    nodes = make_ingress_nodes(4, admission=False)
    assert all(nd.ingress is None for nd in nodes)
    svc = Service("127.0.0.1:0", nodes[0])
    svc.serve_async()
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        code, doc, _ = _post(f"http://{svc.addr}/submit", b"legacy tx")
        assert code == 200
        assert doc == {"submitted": len(b"legacy tx")}
        # batch still works, funneled through the bare submit path
        code, doc, _ = _post(f"http://{svc.addr}/submit/batch",
                             encode_tx_batch([b"l1", b"l2"]))
        assert code == 200 and doc["submitted"] == 2
        try:
            urllib.request.urlopen(
                f"http://{svc.addr}/subscribe?tx={'0' * 64}", timeout=5)
            raise AssertionError("/subscribe with admission off")
        except urllib.error.HTTPError as err:
            assert err.code == 503
        with urllib.request.urlopen(f"http://{svc.addr}/debug/ingress",
                                    timeout=5) as r:
            assert json.loads(r.read()) == {"admission": False}
        _wait_committed(nodes, [b"legacy tx", b"l1", b"l2"])
    finally:
        svc.close()
        for nd in nodes:
            nd.shutdown()


def test_submit_token_auth():
    nodes = make_ingress_nodes(2, submit_token="sekrit")
    svc = Service("127.0.0.1:0", nodes[0])
    svc.serve_async()
    try:
        for url in (f"http://{svc.addr}/submit",
                    f"http://{svc.addr}/submit/batch"):
            try:
                _post(url, b"tx")
                raise AssertionError("unauthenticated submit accepted")
            except urllib.error.HTTPError as err:
                assert err.code == 401
                assert err.headers["WWW-Authenticate"] == "Bearer"
        # wrong token: still 401
        try:
            _post(f"http://{svc.addr}/submit", b"tx",
                  headers={"Authorization": "Bearer nope"})
            raise AssertionError("wrong token accepted")
        except urllib.error.HTTPError as err:
            assert err.code == 401
        code, doc, _ = _post(
            f"http://{svc.addr}/submit", b"authed tx",
            headers={"Authorization": "Bearer sekrit"})
        assert code == 200 and doc["digest"] == tx_digest(b"authed tx")
    finally:
        svc.close()
        for nd in nodes:
            nd.shutdown()


def test_quota_429_with_retry_after():
    nodes = make_ingress_nodes(2, quota_rate=5.0, quota_burst=10.0)
    svc = Service("127.0.0.1:0", nodes[0])
    svc.serve_async()
    try:
        hdrs = {"X-Babble-Client": "greedy"}
        # first batch: the 10-token burst grants 10 of 15
        code, doc, _ = _post(f"http://{svc.addr}/submit/batch",
                             encode_tx_batch(
                                 [b"q%d" % i for i in range(15)]),
                             headers=hdrs)
        assert code == 200
        assert doc["submitted"] == 10 and doc["quota_rejected"] == 5
        assert doc["statuses"][:10] == ["accepted"] * 10
        assert doc["statuses"][10:] == ["quota_rejected"] * 5
        assert doc["retry_after"] >= 1
        # bucket empty: the whole batch rejects -> 429 + Retry-After
        try:
            _post(f"http://{svc.addr}/submit/batch",
                  encode_tx_batch([b"q-again%d" % i for i in range(5)]),
                  headers=hdrs)
            raise AssertionError("over-quota batch accepted")
        except urllib.error.HTTPError as err:
            assert err.code == 429
            assert int(err.headers["Retry-After"]) >= 1
            body = json.loads(err.read())
            assert body["reason"] == "quota"
            assert body["quota_rejected"] == 5
        # a different client has its own bucket
        code, doc, _ = _post(f"http://{svc.addr}/submit", b"other tx",
                             headers={"X-Babble-Client": "polite"})
        assert code == 200
        # the per-client table shows up in /debug/ingress
        with urllib.request.urlopen(f"http://{svc.addr}/debug/ingress",
                                    timeout=5) as r:
            dbg = json.loads(r.read())
        clients = {row["client"]: row for row in dbg["quota"]["clients"]}
        assert clients["greedy"]["rejected"] >= 10
        assert "polite" in clients
    finally:
        svc.close()
        for nd in nodes:
            nd.shutdown()


def test_chunked_body_cap_enforced():
    """The 1 MiB /submit cap holds for chunked bodies too — the 413
    arrives at the moment the decoded size overflows, not after an
    unbounded buffer."""
    nodes = make_ingress_nodes(2)
    svc = Service("127.0.0.1:0", nodes[0])
    svc.serve_async()
    try:
        host, port = svc.addr.split(":")
        # small chunked body: accepted
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        conn.putrequest("POST", "/submit")
        conn.putheader("Transfer-Encoding", "chunked")
        conn.endheaders()
        conn.send(b"9\r\nchunk tx!\r\n0\r\n\r\n")
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        assert resp.status == 200
        assert doc["digest"] == tx_digest(b"chunk tx!")
        conn.close()

        # oversized chunked body: 413 mid-stream, connection closed
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        conn.putrequest("POST", "/submit")
        conn.putheader("Transfer-Encoding", "chunked")
        conn.endheaders()
        chunk = b"x" * 65536
        frame = b"10000\r\n" + chunk + b"\r\n"
        try:
            for _ in range(20):  # 1.25 MiB > the 1 MiB cap
                conn.send(frame)
            conn.send(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # server already answered and closed
        resp = conn.getresponse()
        assert resp.status == 413
        conn.close()

        # absent Content-Length without chunking: 411
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        conn.putrequest("POST", "/submit")
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 411
        conn.close()
    finally:
        svc.close()
        for nd in nodes:
            nd.shutdown()


# ------------------------------------------------------------- chaos


@pytest.mark.slow
def test_overload_shed_before_commit_drop(tmp_path):
    """The overload contract end-to-end: firehose a 3-node cluster
    (FaultyTransport delay making consensus the bottleneck) past
    capacity. Sheds must show up in babble_ingress_shed_total, the
    commit queue must drop NOTHING, every admitted tx must commit
    byte-identically across nodes, and /subscribe must still resolve
    after a node restarts from its FileStore (bootstrap replay +
    store scan)."""
    db0 = str(tmp_path / "node0.db")
    stores = [
        (lambda p, path=db0: FileStore(p, CACHE, path)),
        (lambda p: InmemStore(p, CACHE)),
        (lambda p: InmemStore(p, CACHE)),
    ]
    nodes = make_ingress_nodes(
        3, stores=stores,
        faults={"delay_min": 0.01, "delay_max": 0.04},
        intake_queue=128, ingress_target_delay=0.05,
        ingress_interval=0.1)
    services = [Service("127.0.0.1:0", nd) for nd in nodes]
    for s in services:
        s.serve_async()
    admitted = []
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        # Firehose: batches far larger than the intake queue, no
        # pacing — guaranteed to overflow intake and build standing
        # delay while consensus crawls behind the faulty transport.
        deadline = time.monotonic() + 6.0
        i = 0
        sheds_seen = 0
        while time.monotonic() < deadline:
            txs = [b"overload %d %d" % (i, k) for k in range(512)]
            i += 1
            try:
                code, doc, _ = _post(
                    f"http://{services[i % 3].addr}/submit/batch",
                    encode_tx_batch(txs), timeout=10)
            except urllib.error.HTTPError as err:
                body = json.loads(err.read())
                assert err.code == 429
                assert int(err.headers["Retry-After"]) >= 1
                sheds_seen += body.get("shed", 0)
                continue
            sheds_seen += doc["shed"]
            for tx, st in zip(txs, doc["statuses"]):
                if st == "accepted":
                    admitted.append(tx)
        assert sheds_seen > 0, "firehose never triggered a shed"
        assert admitted, "firehose admitted nothing"

        # every admitted tx commits on every node
        _wait_committed(nodes, admitted, timeout=120.0)

        # byte-identical order across nodes over the common prefix
        streams = [nd.core.get_consensus_transactions() for nd in nodes]
        m = min(len(s) for s in streams)
        assert m > 0
        for s in streams[1:]:
            assert s[:m] == streams[0][:m]

        # the /metrics contract: sheds accounted, zero commit drops
        shed_total = 0.0
        commit_drops = 0.0
        for svc in services:
            with urllib.request.urlopen(
                    f"http://{svc.addr}/metrics", timeout=10) as r:
                samples, _ = promtext.parse(r.read().decode())
            shed_total += sum(v for _lb, v in samples.get(
                "babble_ingress_shed_total", []))
            commit_drops += sum(
                v for lb, v in samples.get(
                    "babble_queue_dropped_total", [])
                if lb.get("queue") == "commit")
        assert shed_total > 0
        assert commit_drops == 0, (
            f"commit path dropped {commit_drops} under overload")

        probe = admitted[0]
        digest = tx_digest(probe)

        # restart node 0 from its FileStore: /subscribe must resolve
        # the pre-restart commit from bootstrap replay / store scan
        services[0].close()
        nodes[0].shutdown()
        entries = make_keyed_peers(3, addr_fn=lambda i: f"addr{i}")
        key0, peer0 = entries[0]
        peers = [p for _, p in entries]
        conf = fast_config(heartbeat=0.01)
        store = FileStore.load(CACHE, db0)
        t0 = InmemTransport("addr0-reborn", timeout=2.0)
        node0 = Node(conf, 0, key0, peers, store, t0, InmemAppProxy())
        node0.init(bootstrap=True)
        svc0 = Service("127.0.0.1:0", node0)
        svc0.serve_async()
        try:
            with urllib.request.urlopen(
                    f"http://{svc0.addr}/subscribe?tx={digest}&timeout=5",
                    timeout=10) as r:
                assert r.status == 200
                sub = json.loads(r.read())
            assert sub["tx"] == digest and sub["round"] >= 0
        finally:
            svc0.close()
            node0.shutdown()
    finally:
        for s in services[1:]:
            s.close()
        for nd in nodes[1:]:
            nd.shutdown()
