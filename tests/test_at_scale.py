"""BASELINE configs 3 and 5 at their STATED scale.

Config 3: 1024 peers with f=341 silent-byzantine peers (below the
n/3 = 341.33 tolerance) through the batched view pipeline.
Config 5: a 4096-peer DAG through the memory-sharded multi-chip
pipeline on the 8-device virtual mesh.

These run the real kernels at real sizes on the CPU mesh, which takes
minutes on this box's single core — they are env-gated
(BABBLE_AT_SCALE=1) so the regular suite stays fast; bench.py's driver
run and CI's at-scale job execute them explicitly."""

import os

import numpy as np
import pytest

at_scale = pytest.mark.skipif(
    os.environ.get("BABBLE_AT_SCALE") != "1",
    reason="set BABBLE_AT_SCALE=1 (minutes-long at-scale runs)")


@at_scale
def test_baseline_config3_1024_peers_f341_byzantine():
    """1024 validators, 341 of them silent — the exact f < n/3 fault
    bound (3*341 = 1023 < 1024), where the supermajority (683) equals
    the live-peer count: every fame decision needs ALL live peers'
    witnesses. Consensus at this size needs a deep DAG — a round spans
    ~14x n events, and decisions land ~3 rounds later, so 131k events
    reach round 6 with ~80k decided (validated: 134s on this box's
    CPU mesh). Consistency is asserted over two TEMPORAL views of the
    network (ancestry-closed prefixes): the earlier order must be a
    prefix of the later one — the monotonicity the reference gets
    from append-only ConsensusEvents (hashgraph.go:826-838)."""
    from babble_tpu.ops.sim import (
        check_view_consistency,
        consensus_views_factored,
        simulate_views,
    )

    n, f = 1024, 341
    silent = np.zeros(n, bool)
    silent[n - f:] = True
    dag, masks, s_rank = simulate_views(
        n, steps=130000, silent=silent, seed=9)
    e = dag.e
    prefix = np.zeros((2, e), bool)
    prefix[0, :100000] = True  # the network 30k events earlier
    prefix[1, :] = True
    out = consensus_views_factored(dag, prefix)
    rr_v = np.asarray(out[4])
    cts_v = np.asarray(out[5])
    rounds = np.asarray(out[0])[1][:e]
    assert rounds.max() >= 4, f"rounds stalled at {rounds.max()}"
    orders = check_view_consistency(dag, rr_v, cts_v, s_ints=s_rank)
    decided = [len(o) for o in orders]
    assert min(decided) > 10_000, f"too little consensus at scale: {decided}"
    assert decided[1] > decided[0], "later view decided no more"
    # silent peers created nothing beyond their (invisible) initial
    # events: no event in the DAG body has a silent creator
    creators = np.asarray(dag.creator[:e])
    assert not np.isin(creators[n:], np.nonzero(silent)[0]).any()


@at_scale
def test_baseline_config5_4096_peer_sharded_dag():
    """4096 validators through the memory-sharded pipeline on the
    8-device mesh: d devices hold a d-times DAG (chain cubes sharded on
    the chain axis), and the result matches the single-device wavefront
    pipeline bit-for-bit.

    Depth note: a round at n=4096 spans ~14n = 57k+ events (measured at
    n=1024: 131k events -> round 6), so at this test's 16k events every
    event sits in round 0 and fame/round-received planes are trivially
    empty — CONSENSUS-deciding depth at scale is exercised by config 3
    (n=1024, 81k decided); this test pins the memory-sharding and
    parity claims at 4096 peers, which once required chunking two
    [level-width, n, n] gathers that would otherwise materialize n^3
    ints (274 GB). Wall: ~1h on this box's single CPU core."""
    import jax
    from jax.sharding import Mesh

    from babble_tpu.ops.dag import synthetic_dag
    from babble_tpu.ops.pipeline import run_pipeline
    from babble_tpu.ops.sharded import sharded_pipeline

    devices = jax.devices()
    assert len(devices) >= 8, "conftest must provision the virtual mesh"
    mesh = Mesh(np.array(devices[:8]), ("sp",))

    n, e = 4096, 16384
    dag, _ = synthetic_dag(n, e, seed=21)
    ref = [np.asarray(x) for x in run_pipeline(dag, engine="wavefront")]
    got = [np.asarray(x) for x in sharded_pipeline(dag, mesh, axis="sp")]
    names = ["rounds", "witness", "witness_table", "famous",
             "round_received", "cts"]
    for name, a, b in zip(names, ref, got):
        assert a.shape == b.shape, name
        assert (a == b).all(), f"{name} mismatch at n=4096"
    # structural sanity: every creator's initial event is a witness,
    # and the witness table's round-0 row is fully populated
    assert ref[1][:e].sum() >= n
    assert (ref[2][0] >= 0).all()
