"""Off-GIL process runtime tests (docs/runtime.md): the shared-memory
columnar hand-off is pickle-free and byte-identical, the procs verify
plane delivers the thread path's exact memo/failure-position contract,
a killed worker's in-flight chunk is dropped + re-verified inline and
the worker respawned, worker telemetry merges into a parent scrape
with a process label, and a mixed threads/procs cluster commits
byte-identical blocks."""

from __future__ import annotations

import os
import signal
import time
from multiprocessing import shared_memory

import pytest

from babble_tpu import crypto
from babble_tpu.hashgraph import InmemStore
from babble_tpu.hashgraph.event import Event
from babble_tpu.net import InmemTransport
from babble_tpu.net.columnar import ColumnarEvents, WireFormatError
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.node import Node, ingest
from babble_tpu.node import runtime as rt
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.proxy import InmemAppProxy
from babble_tpu.telemetry import Registry, promtext

from test_node import CACHE, check_gossip, make_keyed_peers, run_gossip

pytestmark = pytest.mark.skipif(
    not hasattr(os, "sched_getaffinity"),
    reason="procs runtime targets Linux schedulers")


@pytest.fixture(autouse=True)
def _fresh_pool():
    """Each test starts from a cold process pool and leaves no worker
    processes behind for the rest of the suite."""
    rt.reset_for_tests()
    yield
    rt.reset_for_tests()


def _signed_events(count, seed=321, tag=b"rt"):
    key = crypto.key_from_seed(seed)
    pub = crypto.pub_key_bytes(key)
    events = []
    for i in range(count):
        ev = Event.new([tag + b"-%d" % i], ["p0", "p1"], pub, i)
        ev.sign(key)
        ev._sig_ok = None  # drop sign()'s memo: force real verification
        events.append(ev)
    return key, events


# ------------------------------------------------- shared-memory frames


def test_columnar_roundtrip_through_shared_memory_pickle_free():
    """The PR 7 columnar frame crosses a shared_memory segment with no
    pickling: the receiving side decodes VIEWS over the segment's
    buffer (zero-copy), the columns are byte-identical, and re-encoding
    reproduces the original frame bit for bit."""
    _, events = _signed_events(24)
    ce = ColumnarEvents.from_wire_events([ev.to_wire() for ev in events])
    frame = ce.encode()

    shm = shared_memory.SharedMemory(create=True, size=len(frame))
    try:
        shm.buf[:len(frame)] = frame
        # Decode straight over the segment's memoryview — what a
        # worker does. No bytes() copy, no pickle anywhere.
        view = memoryview(shm.buf)[:len(frame)]
        dec = ColumnarEvents.decode(view)
        # The integer columns are numpy VIEWS into the segment, not
        # owned copies: zero-copy is structural, not incidental.
        assert dec.cid.base is not None
        assert dec.ts_ns.base is not None
        for a, b in ((dec.cid, ce.cid), (dec.idx, ce.idx),
                     (dec.sp_idx, ce.sp_idx), (dec.op_cid, ce.op_cid),
                     (dec.op_idx, ce.op_idx), (dec.ts_ns, ce.ts_ns),
                     (dec.tx_counts, ce.tx_counts),
                     (dec.tx_lens, ce.tx_lens)):
            assert a.tolist() == b.tolist()
        assert bytes(dec.sigs) == bytes(ce.sigs)
        assert bytes(dec.tx_blob) == bytes(ce.tx_blob)
        assert dec.encode() == frame
        # Release every view over the segment before close() — a live
        # export makes close() raise BufferError by design.
        del dec, a, b
        view.release()
    finally:
        shm.close()
        shm.unlink()


def test_decode_validate_false_skips_only_integrity_sweeps():
    """validate=False (the post-worker-validation fast path) must skip
    ONLY the O(n) consistency sweeps — the structural length check the
    views depend on still runs."""
    _, events = _signed_events(8)
    frame = ColumnarEvents.from_wire_events(
        [ev.to_wire() for ev in events]).encode()
    a = ColumnarEvents.decode(frame)
    b = ColumnarEvents.decode(frame, validate=False)
    assert a.encode() == b.encode() == frame
    with pytest.raises(WireFormatError):
        ColumnarEvents.decode(frame[:-1], validate=False)


# ------------------------------------------------------- verify plane


def test_procs_verify_parity_including_failure_position():
    """The procs plane delivers the serial/thread contract exactly:
    valid memos True, a corrupted signature False at the identical
    batch position, and a malformed creator point left UNSET so the
    insert loop re-raises at the serial path's position."""
    key, events = _signed_events(16)
    events[3].r = int(events[3].r) ^ 1

    ingest.verify_events(events, workers=2, runtime="procs")
    assert rt.active_pool() is not None, "procs path did not engage"
    assert [ev._sig_ok for ev in events] == \
        [True] * 3 + [False] + [True] * 12

    # Same batch through the thread path: memo-for-memo identical.
    for ev in events:
        ev._sig_ok = None
    ingest.verify_events(events, workers=2, runtime="threads")
    assert [ev._sig_ok for ev in events] == \
        [True] * 3 + [False] + [True] * 12

    # Malformed creator: verdict None -> memo unset (both runtimes).
    _, batch = _signed_events(9, seed=77, tag=b"mc")
    batch[0].body.creator = b"\x00" * 10
    ingest.verify_events(batch, workers=2, runtime="procs")
    assert batch[0]._sig_ok is None
    assert all(ev._sig_ok is True for ev in batch[1:])

    # r outside 32 bytes is an invalid signature (False), exactly as
    # crypto.verify reports it — decided parent-side, no round trip.
    _, batch2 = _signed_events(9, seed=78, tag=b"ov")
    batch2[0].r = 1 << 300
    ingest.verify_events(batch2, workers=2, runtime="procs")
    assert batch2[0]._sig_ok is False
    assert all(ev._sig_ok is True for ev in batch2[1:])


def test_procs_worker_killed_midbatch_drops_and_reverifies_inline():
    """Worker death with a chunk in flight mirrors the cancelled-chunk
    contract (PR 16): the chunk observes its queued wait, counts a
    drop on the shared verify_pool instrument, and is re-verified
    inline so the memos still land — and the supervisor respawns the
    worker for the next batch, counting the restart."""
    key, events = _signed_events(16, seed=91)
    events[3].r = int(events[3].r) ^ 1

    pool = rt.get_pool(2)
    assert pool is not None
    workers = pool.workers()  # spawn both before the kill
    os.kill(workers[0].proc.pid, signal.SIGKILL)
    workers[0].proc.join(timeout=5.0)

    # Suppress the dispatch-time respawn so the dead worker's chunk is
    # genuinely in flight when the death is observed (the respawn-
    # before-dispatch path is supervision working TOO well for this
    # test's purpose).
    real_ensure = pool._ensure
    pool._ensure = lambda i, count_restart=True: pool._workers[i % pool.size]

    inst = ingest._pool_instrument()
    before = inst.snapshot()
    restarts_before = pool._m_restarts.value
    try:
        ingest.verify_events(events, workers=2, runtime="procs")
    finally:
        pool._ensure = real_ensure

    after = inst.snapshot()
    # Two chunks dispatched; the dead worker's chunk waited, dropped,
    # and fell back inline. Both chunks' waits are observed.
    assert after["dropped"] == before["dropped"] + 1
    assert after["waits"] >= before["waits"] + 2
    assert [ev._sig_ok for ev in events] == \
        [True] * 3 + [False] + [True] * 12

    # Next batch: the supervisor respawns the dead worker and the
    # restart is counted; delivery is back to the no-drop path.
    for ev in events:
        ev._sig_ok = None
    ingest.verify_events(events, workers=2, runtime="procs")
    assert pool._m_restarts.value >= restarts_before + 1
    assert [ev._sig_ok for ev in events] == \
        [True] * 3 + [False] + [True] * 12


# -------------------------------------------------------- decode plane


def test_decode_offload_roundtrip_and_malformed_frame():
    """Large frames route through a worker for validation and decode
    identically; a frame whose corruption only the integrity sweeps
    catch still raises WireFormatError through the offload path."""
    key, events = _signed_events(16, seed=55)
    ingest.verify_events(events, workers=2, runtime="procs")  # warm pool

    _, big = _signed_events(600, seed=56, tag=b"z" * 20)
    frame = ColumnarEvents.from_wire_events(
        [ev.to_wire() for ev in big]).encode()
    assert len(frame) >= rt._MIN_DECODE_BYTES
    dec = rt.decode_columnar(frame)
    assert dec.encode() == frame

    # Corrupt one tx_len: the frame's LENGTH is unchanged (the
    # structural check passes) — only the worker-side integrity sweep
    # can reject it.
    import struct

    bad = bytearray(frame)
    off = 4 + 17 + 600 * (5 * 4 + 8 + 64 + 4)
    struct.pack_into("<i", bad, off, 9999)
    with pytest.raises(WireFormatError):
        rt.decode_columnar(bytes(bad))


# -------------------------------------------------- cross-process scrape


def test_worker_registry_scrape_merges_with_process_label():
    """Worker registries cross the pipe and mirror into the parent
    registry with a process label: the batch-size histogram, the
    chunk/event counters, and per-process CPU seconds all render in
    one parse-valid exposition."""
    key, events = _signed_events(16, seed=44)
    ingest.verify_events(events, workers=2, runtime="procs")

    reg = Registry()
    answered = rt.scrape_children(reg)
    assert answered == 2
    text = reg.render()
    samples, _ = promtext.parse(text)

    cpu = {lb["process"]: v
           for lb, v in samples.get("babble_process_cpu_seconds_total", [])}
    assert set(cpu) == {"verify-0", "verify-1"}
    assert all(v > 0 for v in cpu.values())

    chunks = {lb["process"]: v
              for lb, v in samples.get("babble_worker_chunks_total", [])}
    assert set(chunks) == {"verify-0", "verify-1"}
    assert sum(chunks.values()) >= 2  # both chunks of the batch

    # The worker's batch-size histogram arrives process-labelled, so
    # it never collides with the parent's own unlabelled family.
    assert any(lb.get("process") in ("verify-0", "verify-1")
               for lb, _v in samples.get(
                   "babble_verify_batch_size_count", []))

    # Throttle: an immediate re-scrape is skipped (no pipe traffic at
    # scrape cadence), a post-interval one answers again.
    assert rt.scrape_children(reg) == 0


# ------------------------------------------------- mixed-runtime cluster


def _make_mixed_nodes(runtimes):
    transports = [InmemTransport(f"addr{i}", timeout=2.0)
                  for i in range(len(runtimes))]
    connect_all(transports)
    entries = make_keyed_peers(len(runtimes), addr_fn=lambda i: f"addr{i}")
    by_addr = {t.local_addr(): t for t in transports}
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = fast_config(heartbeat=0.01)
        conf.runtime = runtimes[i]
        # Force a real pool even on a 1-core runner: the point is
        # exercising the procs path, not auto-sizing it.
        conf.verify_workers = 2
        store = InmemStore(participants, CACHE)
        node = Node(conf, i, key, peers, store,
                    by_addr[peer.net_addr], InmemAppProxy())
        node.init()
        nodes.append(node)
    return nodes


def test_mixed_runtime_cluster_commits_byte_identical_blocks():
    """A 3-node cluster with one procs node and two threads nodes
    reaches consensus on byte-identical event/tx sequences — the
    runtime is an execution detail, invisible to the protocol — and
    stays byte-identical through a worker SIGKILL mid-run."""
    nodes = _make_mixed_nodes(["procs", "threads", "threads"])
    try:
        run_gossip(nodes, target_round=6, timeout=120.0, shutdown=False)
        # Kill a verify worker while gossip is live: supervision must
        # absorb it (drop + inline re-verify + respawn) without any
        # consensus divergence. The net keeps running (run_gossip
        # already spawned the node loops — don't start them twice),
        # so just keep bombarding until the next round target.
        pool = rt.active_pool()
        if pool is not None:
            os.kill(pool.workers()[0].proc.pid, signal.SIGKILL)
        deadline = time.monotonic() + 120.0
        i = 0
        while time.monotonic() < deadline:
            nodes[i % 3].submit_tx(b"post-kill tx %d" % i)
            i += 1
            if all((nd.core.get_last_consensus_round_index() or 0) >= 10
                   for nd in nodes):
                break
            time.sleep(0.02)
        else:
            rounds = [nd.core.get_last_consensus_round_index()
                      for nd in nodes]
            raise AssertionError(f"post-kill rounds {rounds} < 10")
    finally:
        for nd in nodes:
            nd.shutdown()
    check_gossip(nodes)
    # The procs node really ran the procs plane.
    assert nodes[0].core.runtime == "procs"
    assert nodes[1].core.runtime == "threads"


def test_resolve_runtime_rejects_unknown():
    assert rt.resolve_runtime(None) == "threads"
    assert rt.resolve_runtime("procs") == "procs"
    with pytest.raises(ValueError):
        rt.resolve_runtime("fibers")
