"""Fast-sync: a node hitting SyncLimit catches up from a peer's Frame
instead of re-gossiping history.

The reference leaves fastForward as a stub (node/node.go:432-441) but
ships the machinery it intended to use — GetFrame/Reset
(hashgraph.go:879-1002). These tests cover the completed flow: the
Core-level reset+replay through the serialized frame payload, and the
full node path (SyncLimit -> CatchingUp -> FastForwardRequest ->
reset+replay -> gossip resumes with consensus parity)."""

import json
import random
import time

from babble_tpu import crypto
from babble_tpu.hashgraph.event import event_from_json_obj
from babble_tpu.hashgraph.inmem_store import InmemStore
from babble_tpu.hashgraph.root import Root
from babble_tpu.net.transport import FastForwardResponse
from babble_tpu.node.core import Core

from test_node import make_nodes


def make_cores(n, engine="host"):
    keys = [crypto.key_from_seed(7000 + i) for i in range(n)]
    pubs = ["0x" + crypto.pub_key_bytes(k).hex().upper() for k in keys]
    order = sorted(range(n), key=lambda i: pubs[i])
    keys = [keys[i] for i in order]
    pubs = [pubs[i] for i in order]
    participants = {pk: i for i, pk in enumerate(pubs)}
    cores = [
        Core(i, keys[i], participants, InmemStore(participants, 100000),
             engine=engine)
        for i in range(n)
    ]
    return cores, participants


def gossip_round(cores, a, b):
    known = cores[a].known()
    diff = cores[b].diff(known)
    cores[a].sync(cores[b].to_wire(diff))


def test_core_fast_forward_through_wire_frame():
    """Core.fast_forward over a frame serialized exactly as the
    transport ships it (Root dicts + full Go-JSON events): the fresh
    core's view matches the donor's frame, and continued gossip
    reaches byte-identical consensus order."""
    cores, participants = make_cores(4)
    for c in cores[:3]:
        c.init()
    rng = random.Random(11)
    for step in range(200):
        a, b = rng.sample(range(3), 2)
        gossip_round(cores, a, b)
        if step % 5 == 0:
            cores[a].run_consensus()
    for c in cores[:3]:
        c.run_consensus()
    donor = cores[0]
    assert donor.get_last_consensus_round_index() >= 1

    r0 = donor.get_last_consensus_round_index()
    frame = donor.get_frame()
    # Round-trip through the wire representation.
    resp = FastForwardResponse(
        0,
        roots={pk: r.to_dict() for pk, r in frame.roots.items()},
        events=[json.loads(e.marshal()) for e in frame.events],
    )
    wire = FastForwardResponse.from_dict(resp.to_dict())
    roots = {pk: Root.from_dict(d) for pk, d in wire.roots.items()}
    events = [event_from_json_obj(o) for o in wire.events]

    joiner = cores[3]
    joiner.init()  # its own initial event is wiped by the reset, as in a node
    joiner.fast_forward(roots, events)
    want = donor.known()
    got = joiner.known()
    for pid, ct in got.items():
        if pid == 3:  # the joiner's own wiped chain
            continue
        assert ct <= want[pid], "joiner knows more than the donor"
        assert ct >= 0, "joiner learned nothing from the frame"

    # Continued gossip: joiner pulls from the donor, then both decide.
    for step in range(200):
        a, b = rng.sample(range(4), 2)
        # the joiner's reset store can only serve peers after they know
        # about its post-frame events; keep the flow donor-driven
        gossip_round(cores, a, b)
        if step % 5 == 0:
            cores[a].run_consensus()
    for c in cores:
        c.run_consensus()
    jc = joiner.get_consensus_events()
    dc = donor.get_consensus_events()
    assert jc, "joiner reached no consensus after fast-forward"
    # Within ~2 rounds of the frame base, within-round order can
    # legitimately differ: consensus timestamps are medians over
    # oldest-self-ancestor-to-see chains that the frame truncated.
    # Past that boundary every input to the order is in both DAGs, so
    # the order must match exactly.
    def past_boundary(core, hexes):
        out = []
        for h in hexes:
            ev = core.get_event(h)
            if ev.round_received is not None and ev.round_received > r0 + 2:
                out.append(h)
        return out

    jc_f = past_boundary(joiner, jc)
    dc_f = past_boundary(donor, dc)
    assert jc_f, "no post-boundary consensus to compare"
    m = min(len(jc_f), len(dc_f))
    assert jc_f[:m] == dc_f[:m]


def test_node_fast_sync_catches_up():
    """Full node path over the inmem transport: a late-starting node
    whose first pull trips SyncLimit enters CatchingUp, fast-forwards
    from a peer's Frame, and then gossips normally — its consensus
    order is a contiguous slice of the cluster's."""
    nodes = make_nodes(4, "inmem")
    for nd in nodes:
        nd.conf.sync_limit = 80
    late = nodes[3]
    running = nodes[:3]
    # While the late node is down, keep it out of the running nodes'
    # peer selectors: its unconsumed inmem queue would turn a third of
    # all pulls into 2s timeouts.
    from babble_tpu.node.peer_selector import RandomPeerSelector
    full_peers = {id(nd): nd.peer_selector.peers() for nd in running}
    for nd in running:
        alive = [p for p in nd.peer_selector.peers()
                 if p.net_addr != late.local_addr]
        nd.peer_selector = RandomPeerSelector(alive, nd.local_addr)
    import threading
    stop = threading.Event()

    def bombard():
        # Nodes go quiescent by design when nothing is pending —
        # continuous submission keeps the DAG growing (the reference's
        # bombardAndWait, node_test.go:507-545).
        i = 0
        while not stop.is_set():
            try:
                running[i % len(running)].submit_tx(
                    f"fastsync tx {i}".encode())
            except Exception:
                pass
            i += 1
            time.sleep(0.005)

    try:
        for nd in running:
            nd.run_async(gossip=True)
        threading.Thread(target=bombard, daemon=True).start()
        deadline = time.monotonic() + 120.0
        committed = lambda: min(  # noqa: E731
            len(nd.core.get_consensus_events()) for nd in running)
        while time.monotonic() < deadline and committed() < 300:
            time.sleep(0.25)
        assert committed() >= 300, "cluster did not advance enough"

        # Bring the late node up and restore full selectors.
        for nd in running:
            nd.peer_selector = RandomPeerSelector(
                full_peers[id(nd)], nd.local_addr)
        late.run_async(gossip=True)
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline and not (
            late.fast_forwards >= 1
            and len(late.core.get_consensus_events()) > 0
        ):
            time.sleep(0.25)
        assert late.fast_forwards >= 1, "late node never fast-forwarded"
        lc = late.core.get_consensus_events()
        assert lc, "late node reached no consensus after fast-forward"
        # Skip the frame-boundary region (see the core-level test):
        # compare from the first event BOTH lists contain, two rounds
        # past the late node's first received round. Right after the
        # fast-forward the late node may only have boundary-region
        # commits, so refresh both lists until comparable post-boundary
        # consensus exists.
        deadline = time.monotonic() + 60.0
        lc_f: list = []
        ref: list = []
        while time.monotonic() < deadline and not lc_f:
            time.sleep(0.25)
            lc = late.core.get_consensus_events()
            ref = nodes[0].core.get_consensus_events()
            lrr = [late.core.get_event(h).round_received for h in lc]
            known = [r for r in lrr if r is not None]
            if not known:
                continue
            base = min(known)
            lc_f = [h for h, r in zip(lc, lrr)
                    if r is not None and r > base + 2]
            ref_set = set(ref)
            lc_f = [h for h in lc_f if h in ref_set]
        assert lc_f, "no comparable post-boundary consensus"
        start = ref.index(lc_f[0])
        # ref may contain boundary events the late node ordered
        # differently; compare the subsequence of ref restricted to
        # the late node's post-boundary events
        ref_r = [h for h in ref[start:] if h in set(lc_f)]
        m = min(len(lc_f), len(ref_r))
        assert m > 0
        assert lc_f[:m] == ref_r[:m]
    finally:
        stop.set()
        for nd in nodes:
            nd.shutdown()
