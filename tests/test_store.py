"""Store tests: InmemStore CRUD + error types (reference
hashgraph/inmem_store_test.go:35-176) and FileStore write-through,
reload, and topological replay (reference badger_store_test.go)."""

from __future__ import annotations

import os

import pytest

from babble_tpu import crypto
from babble_tpu.common import StoreError, StoreErrType, is_store_err
from babble_tpu.gojson import Timestamp
from babble_tpu.hashgraph import Event, FileStore, Hashgraph, InmemStore
from babble_tpu.hashgraph.event import event_from_json_obj
import json


def make_participants(n, seed=7000):
    keys = [crypto.key_from_seed(seed + i) for i in range(n)]
    pubs = ["0x" + crypto.pub_key_bytes(k).hex().upper() for k in keys]
    order = sorted(range(n), key=lambda i: pubs[i])
    participants = {pubs[i]: rank for rank, i in enumerate(order)}
    return keys, pubs, participants


def signed_event(key, pub_hex, parents, index, ts):
    ev = Event.new([b"tx"], parents, bytes.fromhex(pub_hex[2:]), index,
                   timestamp=Timestamp(ts))
    ev.sign(key)
    return ev


def test_inmem_store_crud_and_errors():
    keys, pubs, participants = make_participants(3)
    store = InmemStore(participants, 100)

    with pytest.raises(StoreError) as ei:
        store.get_event("0xDEADBEEF")
    assert is_store_err(ei.value, StoreErrType.KEY_NOT_FOUND)

    ev = signed_event(keys[0], pubs[0], ["", ""], 0, 10**18)
    store.set_event(ev)
    assert store.get_event(ev.hex()) is ev
    assert store.participant_event(pubs[0], 0) == ev.hex()
    last, is_root = store.last_from(pubs[0])
    assert last == ev.hex() and not is_root

    # unknown participant: the participant cache misses first
    with pytest.raises(StoreError) as ei:
        store.last_from("0xFF")
    assert is_store_err(ei.value, StoreErrType.KEY_NOT_FOUND)

    known = store.known()
    assert known[participants[pubs[0]]] == 0
    assert known[participants[pubs[1]]] == -1


def test_event_json_roundtrip():
    keys, pubs, _ = make_participants(1)
    ev = signed_event(keys[0], pubs[0], ["", ""], 0, 1_600_000_000_123_456_789)
    data = ev.marshal()
    ev2 = event_from_json_obj(json.loads(data))
    assert ev2.marshal() == data
    assert ev2.hex() == ev.hex()
    assert ev2.verify()


def test_file_store_write_through_and_reload(tmp_path):
    keys, pubs, participants = make_participants(2)
    path = str(tmp_path / "store.db")
    store = FileStore(participants, 100, path)

    ev0 = signed_event(keys[0], pubs[0], ["", ""], 0, 10**18)
    ev1 = signed_event(keys[1], pubs[1], ["", ""], 0, 10**18 + 1)
    ev0.topological_index = 0
    ev1.topological_index = 1
    store.set_event(ev0)
    store.set_event(ev1)
    store.close()

    # reload from disk: participants + events + replay order survive
    store2 = FileStore.load(100, path)
    assert store2.participants() == participants
    got = store2.get_event(ev0.hex())
    assert got.hex() == ev0.hex()
    assert got.verify()
    topo = [e.hex() for e in store2.db_topological_events()]
    assert topo == [ev0.hex(), ev1.hex()]
    # db fallback for participant queries (fresh inmem cache is empty)
    assert store2.participant_event(pubs[0], 0) == ev0.hex()
    store2.close()


def test_file_store_bootstrap_consensus(tmp_path):
    """Insert a full fixture DAG through a FileStore-backed hashgraph,
    reload from disk, bootstrap, and compare consensus state — the
    TestBootstrap analog (reference hashgraph_test.go:1351)."""
    from fixtures import build_consensus_graph

    path = str(tmp_path / "hg.db")

    # run consensus against a FileStore
    h, b = build_consensus_graph.__wrapped__() if hasattr(
        build_consensus_graph, "__wrapped__") else build_consensus_graph()
    participants = b.participants()
    fs = FileStore(participants, 1000, path)
    h2 = Hashgraph(participants, fs)
    for ev in b.ordered_events:
        # fresh copies: the fixture events carry coordinate state
        ev2 = event_from_json_obj(json.loads(ev.marshal()))
        h2.insert_event(ev2, True)
    h2.run_consensus()
    expected_order = h2.consensus_events()
    expected_last_round = h2.last_consensus_round
    assert expected_order, "fixture produced no consensus"
    fs.close()

    # reload + bootstrap
    fs2 = FileStore.load(1000, path)
    h3 = Hashgraph(participants, fs2)
    h3.bootstrap()
    assert h3.consensus_events() == expected_order
    assert h3.last_consensus_round == expected_last_round
    fs2.close()
