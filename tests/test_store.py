"""Store tests: InmemStore CRUD + error types (reference
hashgraph/inmem_store_test.go:35-176) and FileStore write-through,
reload, and topological replay (reference badger_store_test.go)."""

from __future__ import annotations

import os

import pytest

from babble_tpu import crypto
from babble_tpu.common import StoreError, StoreErrType, is_store_err
from babble_tpu.gojson import Timestamp
from babble_tpu.hashgraph import Event, FileStore, Hashgraph, InmemStore
from babble_tpu.hashgraph.event import event_from_json_obj
import json


def make_participants(n, seed=7000):
    keys = [crypto.key_from_seed(seed + i) for i in range(n)]
    pubs = ["0x" + crypto.pub_key_bytes(k).hex().upper() for k in keys]
    order = sorted(range(n), key=lambda i: pubs[i])
    participants = {pubs[i]: rank for rank, i in enumerate(order)}
    return keys, pubs, participants


def signed_event(key, pub_hex, parents, index, ts):
    ev = Event.new([b"tx"], parents, bytes.fromhex(pub_hex[2:]), index,
                   timestamp=Timestamp(ts))
    ev.sign(key)
    return ev


def test_inmem_store_crud_and_errors():
    keys, pubs, participants = make_participants(3)
    store = InmemStore(participants, 100)

    with pytest.raises(StoreError) as ei:
        store.get_event("0xDEADBEEF")
    assert is_store_err(ei.value, StoreErrType.KEY_NOT_FOUND)

    ev = signed_event(keys[0], pubs[0], ["", ""], 0, 10**18)
    store.set_event(ev)
    assert store.get_event(ev.hex()) is ev
    assert store.participant_event(pubs[0], 0) == ev.hex()
    last, is_root = store.last_from(pubs[0])
    assert last == ev.hex() and not is_root

    # unknown participant: the participant cache misses first
    with pytest.raises(StoreError) as ei:
        store.last_from("0xFF")
    assert is_store_err(ei.value, StoreErrType.KEY_NOT_FOUND)

    known = store.known()
    assert known[participants[pubs[0]]] == 0
    assert known[participants[pubs[1]]] == -1


def test_event_json_roundtrip():
    keys, pubs, _ = make_participants(1)
    ev = signed_event(keys[0], pubs[0], ["", ""], 0, 1_600_000_000_123_456_789)
    data = ev.marshal()
    ev2 = event_from_json_obj(json.loads(data))
    assert ev2.marshal() == data
    assert ev2.hex() == ev.hex()
    assert ev2.verify()


def test_file_store_write_through_and_reload(tmp_path):
    keys, pubs, participants = make_participants(2)
    path = str(tmp_path / "store.db")
    store = FileStore(participants, 100, path)

    ev0 = signed_event(keys[0], pubs[0], ["", ""], 0, 10**18)
    ev1 = signed_event(keys[1], pubs[1], ["", ""], 0, 10**18 + 1)
    ev0.topological_index = 0
    ev1.topological_index = 1
    store.set_event(ev0)
    store.set_event(ev1)
    store.close()

    # reload from disk: participants + events + replay order survive
    store2 = FileStore.load(100, path)
    assert store2.participants() == participants
    got = store2.get_event(ev0.hex())
    assert got.hex() == ev0.hex()
    assert got.verify()
    topo = [e.hex() for e in store2.db_topological_events()]
    assert topo == [ev0.hex(), ev1.hex()]
    # db fallback for participant queries (fresh inmem cache is empty)
    assert store2.participant_event(pubs[0], 0) == ev0.hex()
    store2.close()


def test_file_store_bootstrap_consensus(tmp_path):
    """Insert a full fixture DAG through a FileStore-backed hashgraph,
    reload from disk, bootstrap, and compare consensus state — the
    TestBootstrap analog (reference hashgraph_test.go:1351)."""
    from fixtures import build_consensus_graph

    path = str(tmp_path / "hg.db")

    # run consensus against a FileStore
    h, b = build_consensus_graph.__wrapped__() if hasattr(
        build_consensus_graph, "__wrapped__") else build_consensus_graph()
    participants = b.participants()
    fs = FileStore(participants, 1000, path)
    h2 = Hashgraph(participants, fs)
    for ev in b.ordered_events:
        # fresh copies: the fixture events carry coordinate state
        ev2 = event_from_json_obj(json.loads(ev.marshal()))
        h2.insert_event(ev2, True)
    h2.run_consensus()
    expected_order = h2.consensus_events()
    expected_last_round = h2.last_consensus_round
    assert expected_order, "fixture produced no consensus"
    fs.close()

    # reload + bootstrap
    fs2 = FileStore.load(1000, path)
    h3 = Hashgraph(participants, fs2)
    h3.bootstrap()
    assert h3.consensus_events() == expected_order
    assert h3.last_consensus_round == expected_last_round
    fs2.close()


# ------------------------------------------------------------------
# FileStore cache-eviction -> db fallback, per method (the reference's
# badger_store_test.go:66-491 checks this cache-vs-db layering and the
# error type each method returns).


def _evicted_file_store(tmp_path, cache=4, per_creator=12):
    """A FileStore whose tiny inmem layer has provably evicted the
    early events: two creators, `per_creator` events each, LRU size
    `cache` << total."""
    keys, pubs, participants = make_participants(2)
    path = os.path.join(tmp_path, "evict.db")
    fs = FileStore(participants, cache, path)
    heads = {p: "" for p in pubs}
    all_events = {p: [] for p in pubs}
    ts = 1_700_000_000_000_000_000
    for idx in range(per_creator):
        for k, p in zip(keys, pubs):
            ev = signed_event(k, p, [heads[p], ""], idx, ts)
            ts += 1000
            ev.topological_index = idx
            fs.set_event(ev)
            heads[p] = ev.hex()
            all_events[p].append(ev)
    return fs, pubs, all_events


def test_file_store_get_event_falls_back_to_db(tmp_path):
    fs, pubs, evs = _evicted_file_store(tmp_path)
    early = evs[pubs[0]][0]
    # provably evicted from the inmem layer...
    with pytest.raises(StoreError):
        fs.inmem.get_event(early.hex())
    # ...but the store still serves it, byte-identically, from sqlite.
    got = fs.get_event(early.hex())
    assert got.marshal() == early.marshal()
    assert got.topological_index == early.topological_index
    # and a genuinely unknown key is KEY_NOT_FOUND.
    with pytest.raises(StoreError) as ei:
        fs.get_event("0xDEAD")
    assert is_store_err(ei.value, StoreErrType.KEY_NOT_FOUND)
    fs.close()


def test_file_store_has_event_falls_back_to_db(tmp_path):
    fs, pubs, evs = _evicted_file_store(tmp_path)
    early = evs[pubs[0]][0]
    assert not fs.inmem.has_event(early.hex())
    assert fs.has_event(early.hex())
    assert not fs.has_event("0xDEAD")
    fs.close()


def test_file_store_participant_events_falls_back_to_db(tmp_path):
    fs, pubs, evs = _evicted_file_store(tmp_path)
    p = pubs[0]
    # the rolling window no longer reaches skip=-1 (TooLate inmem)...
    with pytest.raises(StoreError) as ei:
        fs.inmem.participant_events(p, -1)
    assert is_store_err(ei.value, StoreErrType.TOO_LATE)
    # ...the db serves the complete history, in index order.
    full = fs.participant_events(p, -1)
    assert full == [e.hex() for e in evs[p]]
    # and a mid-history skip too.
    assert fs.participant_events(p, 5) == [e.hex() for e in evs[p][6:]]
    fs.close()


def test_file_store_participant_event_falls_back_to_db(tmp_path):
    fs, pubs, evs = _evicted_file_store(tmp_path)
    p = pubs[0]
    with pytest.raises(StoreError):
        fs.inmem.participant_event(p, 0)
    assert fs.participant_event(p, 0) == evs[p][0].hex()
    with pytest.raises(StoreError) as ei:
        fs.participant_event(p, 999)
    assert is_store_err(ei.value, StoreErrType.KEY_NOT_FOUND)
    fs.close()


def test_file_store_rounds_fall_back_to_db(tmp_path):
    from babble_tpu.hashgraph.round_info import RoundInfo

    keys, pubs, participants = make_participants(2)
    fs = FileStore(participants, 4, os.path.join(tmp_path, "r.db"))
    for r in range(10):
        ri = RoundInfo()
        ri.add_event(f"0xE{r:02d}", r % 2 == 0)
        fs.set_round(r, ri)
    # round 0 evicted from the LRU...
    with pytest.raises(StoreError):
        fs.inmem.get_round(0)
    got = fs.get_round(0)
    assert "0xE00" in got.events and got.events["0xE00"].witness
    # witnesses/events helpers ride the same fallback
    assert fs.round_witnesses(0) == ["0xE00"]
    assert fs.round_events(0) == 1
    assert fs.last_round() == 9
    with pytest.raises(StoreError) as ei:
        fs.get_round(77)
    assert is_store_err(ei.value, StoreErrType.KEY_NOT_FOUND)
    fs.close()


def test_file_store_roots_and_errors(tmp_path):
    keys, pubs, participants = make_participants(2)
    fs = FileStore(participants, 4, os.path.join(tmp_path, "roots.db"))
    root = fs.get_root(pubs[0])
    assert root.index == -1 and root.round == -1
    with pytest.raises(StoreError) as ei:
        fs.get_root("0xNOBODY")
    assert is_store_err(ei.value, StoreErrType.NO_ROOT)
    fs.close()


def test_file_store_blocks_fall_back_to_db(tmp_path):
    from babble_tpu.hashgraph.block import Block

    keys, pubs, participants = make_participants(2)
    fs = FileStore(participants, 4, os.path.join(tmp_path, "b.db"))
    for rr in range(10):
        fs.set_block(Block(rr, [f"tx{rr}".encode()]))
    with pytest.raises(StoreError):
        fs.inmem.get_block(0)
    got = fs.get_block(0)
    assert got.round_received == 0 and got.transactions == [b"tx0"]
    with pytest.raises(StoreError) as ei:
        fs.get_block(99)
    assert is_store_err(ei.value, StoreErrType.KEY_NOT_FOUND)
    fs.close()


def test_file_store_reload_serves_evicted_history(tmp_path):
    """Close + FileStore.load: the reloaded store's db layer still has
    everything, including what the pre-close LRU had evicted."""
    fs, pubs, evs = _evicted_file_store(tmp_path)
    fs.close()
    fs2 = FileStore.load(4, os.path.join(tmp_path, "evict.db"))
    early = evs[pubs[0]][0]
    assert fs2.get_event(early.hex()).marshal() == early.marshal()
    assert fs2.participant_events(pubs[0], -1) == [
        e.hex() for e in evs[pubs[0]]]
    fs2.close()


def _chain(keys, pubs, store, n, start_ts=10**18):
    """Insert an n-event self-parent chain for participant 0."""
    evs, prev = [], ""
    for i in range(n):
        ev = signed_event(keys[0], pubs[0], [prev, ""], i, start_ts + i)
        store.set_event(ev)
        evs.append(ev)
        prev = ev.hex()
    return evs


def test_inmem_passed_index_rejects_unknown_hash_beyond_window():
    """An event reusing an index that aged out of the rolling window is
    NOT absorbed as an idempotent refresh: once neither the window nor
    the LRU can vouch for the hash previously stored there, a differing
    hash is indistinguishable from a fork and must raise PASSED_INDEX."""
    keys, pubs, participants = make_participants(1)
    store = InmemStore(participants, 5)
    evs = _chain(keys, pubs, store, 12)

    # index 0 aged out of the window AND its hash fell out of the LRU
    with pytest.raises(StoreError) as ei:
        store.participant_event(pubs[0], 0)
    assert is_store_err(ei.value, StoreErrType.TOO_LATE)
    assert not store.event_cache.get(evs[0].hex())[1]

    # a DIFFERENT event at that index (a fork on old history) raises
    forged = signed_event(keys[0], pubs[0], ["", ""], 0, 10**18 + 999)
    assert forged.hex() != evs[0].hex()
    with pytest.raises(StoreError) as ei:
        store.set_event(forged)
    assert is_store_err(ei.value, StoreErrType.PASSED_INDEX)

    # a re-store the cache can still vouch for stays idempotent
    store.set_event(evs[11])
    assert store.participant_event(pubs[0], 11) == evs[11].hex()


def test_file_store_passed_index_falls_back_to_db(tmp_path):
    """FileStore answers the beyond-window re-store from its database:
    the hash on disk at (creator, idx) distinguishes an idempotent
    refresh (accepted) from a fork (PASSED_INDEX)."""
    keys, pubs, participants = make_participants(1)
    fs = FileStore(participants, 5, os.path.join(tmp_path, "pi.db"))
    evs = _chain(keys, pubs, fs, 12)

    # genuine re-store of old history: db vouches, no raise
    fs.set_event(evs[0])

    forged = signed_event(keys[0], pubs[0], ["", ""], 0, 10**18 + 999)
    with pytest.raises(StoreError) as ei:
        fs.set_event(forged)
    assert is_store_err(ei.value, StoreErrType.PASSED_INDEX)
    fs.close()
