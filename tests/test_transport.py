"""Transport unit suite — the reference's generic transport tests plus
TCP-specific pooling/framing tests (net/transport_test.go:28-164,
net/net_transport_test.go:13-245), ported to the inmem and TCP
transports behind the same Transport protocol."""

import queue
import socket
import threading
import time

import pytest

from babble_tpu.gojson import Timestamp
from babble_tpu.hashgraph.event import WireBody, WireEvent
from babble_tpu.net import InmemTransport, TCPTransport
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.net.transport import (
    EagerSyncRequest,
    EagerSyncResponse,
    FastForwardRequest,
    FastForwardResponse,
    SyncRequest,
    SyncResponse,
    TransportError,
)


def make_pair(kind, **kw):
    if kind == "inmem":
        t1 = InmemTransport("addrA", timeout=1.0)
        t2 = InmemTransport("addrB", timeout=1.0)
        connect_all([t1, t2])
    else:
        t1 = TCPTransport("127.0.0.1:0", timeout=1.0, **kw)
        t2 = TCPTransport("127.0.0.1:0", timeout=1.0, **kw)
    return t1, t2


def wire_event():
    return WireEvent(
        WireBody(
            transactions=None,
            self_parent_index=1,
            other_parent_creator_id=10,
            other_parent_index=0,
            creator_id=9,
            timestamp=Timestamp(1_700_000_000_000_000_123),
            index=1,
        ),
        r=12345,
        s=67890,
    )


def serve(trans, expect_type, resp, n=1, fail=None):
    """Answer n inbound RPCs with `resp` (reference's listener goroutine)."""

    def loop():
        for _ in range(n):
            try:
                rpc = trans.consumer().get(timeout=5.0)
            except queue.Empty:
                return
            assert isinstance(rpc.command, expect_type)
            rpc.respond(resp, fail)

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return t


@pytest.mark.parametrize("kind", ["inmem", "tcp"])
def test_start_stop(kind):
    t1, t2 = make_pair(kind)
    t1.close()
    t2.close()


@pytest.mark.parametrize("kind", ["inmem", "tcp"])
def test_sync_round_trip(kind):
    """TestTransport_Sync / TestNetworkTransport_Sync: request fields
    and the full response (sync_limit, events, known) survive the
    round trip byte-for-byte."""
    t1, t2 = make_pair(kind)
    try:
        args = SyncRequest(from_id=0, known={0: 1, 1: 2, 2: 3})
        resp = SyncResponse(
            from_id=1,
            events=[wire_event()],
            known={0: 4, 1: 5, 2: 6},
        )

        got_cmd = {}

        def loop():
            rpc = t1.consumer().get(timeout=5.0)
            got_cmd["known"] = dict(rpc.command.known)
            got_cmd["from_id"] = rpc.command.from_id
            rpc.respond(resp, None)

        threading.Thread(target=loop, daemon=True).start()
        out = t2.sync(t1.local_addr(), args)
        assert got_cmd == {"known": {0: 1, 1: 2, 2: 3}, "from_id": 0}
        assert out.from_id == 1
        assert out.sync_limit is False
        assert out.known == {0: 4, 1: 5, 2: 6}
        assert len(out.events) == 1
        # The TCP pair negotiates the columnar wire, so the payload
        # arrives as a packed batch; the legacy view is equivalent.
        events = (out.events if isinstance(out.events, list)
                  else out.events.to_wire_events())
        we = events[0]
        assert we.body.self_parent_index == 1
        assert we.body.other_parent_creator_id == 10
        assert we.body.creator_id == 9
        assert int(we.r) == 12345 and int(we.s) == 67890
    finally:
        t1.close()
        t2.close()


@pytest.mark.parametrize("kind", ["inmem", "tcp"])
def test_eager_sync_round_trip(kind):
    t1, t2 = make_pair(kind)
    try:
        serve(t1, EagerSyncRequest, EagerSyncResponse(1, True))
        out = t2.eager_sync(
            t1.local_addr(), EagerSyncRequest(0, [wire_event()]))
        assert out.from_id == 1 and out.success is True
    finally:
        t1.close()
        t2.close()


@pytest.mark.parametrize("kind", ["inmem", "tcp"])
def test_fast_forward_round_trip(kind):
    t1, t2 = make_pair(kind)
    try:
        resp = FastForwardResponse(
            1,
            roots={"0xAB": {"X": "h1", "Y": "h2", "Index": 3, "Round": 2,
                            "Others": {}}},
            events=[{"Body": {"Index": 0}}],
        )
        serve(t1, FastForwardRequest, resp)
        out = t2.fast_forward(t1.local_addr(), FastForwardRequest(0))
        assert out.from_id == 1
        assert out.roots["0xAB"]["Index"] == 3
        assert out.events == [{"Body": {"Index": 0}}]
    finally:
        t1.close()
        t2.close()


@pytest.mark.parametrize("kind", ["inmem", "tcp"])
def test_error_response_propagates(kind):
    """A handler error comes back as a TransportError at the caller
    (the TCP framing carries it as the error-string line)."""
    t1, t2 = make_pair(kind)
    try:
        serve(t1, SyncRequest, SyncResponse(1), fail=TransportError("busy"))
        with pytest.raises(TransportError):
            t2.sync(t1.local_addr(), SyncRequest(0, {}))
    finally:
        t1.close()
        t2.close()


def test_inmem_unknown_peer():
    t1 = InmemTransport("addrA", timeout=0.3)
    with pytest.raises(TransportError):
        t1.sync("nowhere", SyncRequest(0, {}))
    t1.close()


def test_inmem_timeout_on_nonconsuming_peer():
    """A wedged peer (nobody draining the consumer) must surface as a
    timeout, not a hang."""
    t1, t2 = make_pair("inmem")
    try:
        t0 = time.monotonic()
        with pytest.raises(TransportError):
            t2.sync(t1.local_addr(), SyncRequest(0, {}))
        assert time.monotonic() - t0 < 5.0
    finally:
        t1.close()
        t2.close()


def test_tcp_pooled_conn_reuse():
    """TestNetworkTransport_PooledConn: back-to-back and concurrent
    RPCs reuse pooled connections, and the pool never exceeds
    max_pool."""
    t1, t2 = make_pair("tcp", max_pool=2)
    try:
        resp = SyncResponse(1, events=[wire_event()])
        serve(t1, SyncRequest, resp, n=40)
        args = SyncRequest(0, {0: 1})

        errs = []

        def worker():
            try:
                for _ in range(5):
                    out = t2.sync(t1.local_addr(), args)
                    assert out.from_id == 1
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20.0)
        assert not errs, errs
        with t2._pool_lock:
            pooled = sum(len(v) for v in t2._pool.values())
        assert 1 <= pooled <= 2, f"pool size {pooled} vs max_pool 2"
    finally:
        t1.close()
        t2.close()


def test_tcp_garbage_frame_does_not_kill_listener():
    """A connection that sends a bogus tag + junk must not take the
    transport down; real RPCs still work afterwards."""
    t1, t2 = make_pair("tcp")
    try:
        host, port = t1.local_addr().rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=1.0)
        s.sendall(b"\xff this is not a frame\n")
        time.sleep(0.2)
        s.close()

        serve(t1, SyncRequest, SyncResponse(1))
        out = t2.sync(t1.local_addr(), SyncRequest(0, {}))
        assert out.from_id == 1
    finally:
        t1.close()
        t2.close()


def test_tcp_sync_after_peer_restart():
    """Pooled connections to a dead listener are detected and replaced:
    after the peer closes, a call errors; the pool does not serve
    stale sockets forever."""
    t1, t2 = make_pair("tcp")
    addr = t1.local_addr()
    try:
        serve(t1, SyncRequest, SyncResponse(1))
        out = t2.sync(addr, SyncRequest(0, {}))
        assert out.from_id == 1
        t1.close()
        time.sleep(0.1)
        with pytest.raises(TransportError):
            t2.sync(addr, SyncRequest(0, {}))
    finally:
        t1.close()
        t2.close()


def test_tcp_response_timeout_and_consumer_buffer_params():
    """The inbound-response wait and consumer queue capacity are
    constructor parameters; a full consumer queue is answered with a
    TransportError immediately instead of stalling the handler
    thread."""
    t1 = TCPTransport("127.0.0.1:0", timeout=1.0)
    # Nobody drains t2's consumer: one slot, short handler wait.
    t2 = TCPTransport("127.0.0.1:0", timeout=2.0,
                      response_timeout=0.4, consumer_buffer=1)
    assert t2._response_timeout == 0.4
    assert t2._consumer.maxsize == 1
    # Default derivation unchanged: 10x timeout.
    assert t1._response_timeout == 10.0
    results = {}

    def call(tag):
        t0 = time.monotonic()
        try:
            t1.sync(t2.local_addr(), SyncRequest(0, {}))
            results[tag] = ("ok", time.monotonic() - t0)
        except TransportError as exc:
            results[tag] = (str(exc), time.monotonic() - t0)

    try:
        first = threading.Thread(target=call, args=("first",))
        first.start()
        time.sleep(0.15)  # first RPC now fills the 1-slot queue
        second = threading.Thread(target=call, args=("second",))
        second.start()
        first.join(timeout=5.0)
        second.join(timeout=5.0)
        # Queue full: rejected immediately, not after a timeout.
        msg, dt = results["second"]
        assert "consumer queue full" in msg, results
        assert dt < 0.3, f"full-queue rejection took {dt:.2f}s"
        # Undrained RPC: the handler reported its (shortened) timeout.
        msg, dt = results["first"]
        assert "rpc handler timed out" in msg, results
    finally:
        t1.close()
        t2.close()
