"""Fork / equivocation detection end-to-end (docs/observability.md
"Consensus health"): the insert path surfaces two-signed-events-at-
one-index as ForkError + persisted evidence + the babble_forks_total
counter; the chaos transport's equivocation injector proves detection
fires within one gossip round in a live net while the honest nodes'
consensus order stays byte-identical; FileStore evidence survives
restart."""

from __future__ import annotations

import os
import time

import pytest

from babble_tpu import crypto
from babble_tpu.hashgraph import Event, FileStore, ForkError, InmemStore
from babble_tpu.net import FaultyTransport, InmemTransport
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.node import Core, Node
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.proxy import InmemAppProxy

from test_node import check_gossip, init_cores, make_keyed_peers, \
    synchronize_cores

CACHE = 10000


def _forge_at_head(core, key):
    """A signed conflicting event at the creator's CURRENT head index:
    same creator, same index, same self-parent, different payload —
    textbook equivocation, provable by the two signatures."""
    head = core.get_head()
    assert head.index() >= 1, "forge below the initial event"
    forged = Event.new([b"equivocation payload"],
                       [head.self_parent(), ""],
                       core.pub_key(), head.index())
    forged.sign(key)
    assert forged.hex() != head.hex()
    forged.set_wire_info(
        head.index() - 1, -1, -1,
        core.participants[core.hex_id()])
    return head, forged


# ---------------------------------------------------------------- unit


def test_insert_path_detects_fork_and_records_evidence():
    cores = init_cores(2)
    keys = [crypto.key_from_seed(5000 + i) for i in range(2)]
    # init_cores sorts by pubkey: map keys to cores by hex id.
    by_hex = {"0x" + crypto.pub_key_bytes(k).hex().upper(): k
              for k in keys}
    synchronize_cores(cores, 0, 1, [b"a"])  # core1 head now index 1
    synchronize_cores(cores, 1, 0)          # core0 learns core1's chain

    victim_key = by_hex[cores[1].hex_id()]
    head, forged = _forge_at_head(cores[1], victim_key)

    with pytest.raises(ForkError, match="equivocation"):
        cores[0].hg.insert_event(forged, False)

    evidence = cores[0].fork_evidence()
    assert len(evidence) == 1
    rec = evidence[0]
    assert rec["creator"] == cores[1].hex_id()
    assert rec["index"] == head.index()
    assert rec["existing"] == head.hex()
    assert rec["forged"] == forged.hex()
    assert cores[0].forks_detected() == 1
    # Evidence carries the full signed proof: it re-parses and its
    # signature verifies.
    import json

    from babble_tpu.hashgraph.event import event_from_json_obj

    proof = event_from_json_obj(json.loads(rec["event_json"]))
    assert proof.verify() and proof.hex() == forged.hex()

    # A replayed forgery re-raises but dedupes: one record, one count.
    with pytest.raises(ForkError):
        cores[0].hg.insert_event(forged, False)
    assert len(cores[0].fork_evidence()) == 1
    assert cores[0].forks_detected() == 1


def test_benign_insert_failures_record_no_evidence():
    cores = init_cores(2)
    synchronize_cores(cores, 0, 1, [b"a"])
    # An unsigned event at a taken index proves nothing about the
    # creator: rejected, but NOT fork evidence.
    head = cores[1].get_head()
    fake = Event.new([b"junk"], [head.self_parent(), ""],
                     cores[1].pub_key(), head.index())
    wrong_key = crypto.key_from_seed(999)
    fake.sign(wrong_key)
    with pytest.raises(Exception):
        cores[1].hg.insert_event(fake, False)
    assert cores[1].fork_evidence() == []
    assert cores[1].forks_detected() == 0


def test_fork_evidence_survives_filestore_restart(tmp_path):
    path = str(tmp_path / "forks.db")
    entries = make_keyed_peers(2)
    participants = {p.pub_key_hex: i for i, (_k, p) in enumerate(entries)}
    store = FileStore(participants, 100, path)
    cores = [Core(i, key, participants, InmemStore(participants, CACHE))
             for i, (key, _p) in enumerate(entries)]
    for c in cores:
        c.init()
    synchronize_cores(cores, 0, 1, [b"a"])
    head, forged = _forge_at_head(cores[1], entries[1][0])
    from babble_tpu.hashgraph.health import fork_evidence_record

    rec = fork_evidence_record(head.hex(), forged)
    assert store.add_fork_evidence(rec) is True
    assert store.add_fork_evidence(rec) is False  # deduped
    store.close()

    reopened = FileStore.load(100, path)
    try:
        got = reopened.fork_evidence()
        assert len(got) == 1
        assert got[0]["forged"] == forged.hex()
        assert got[0]["creator"] == cores[1].hex_id()
    finally:
        reopened.close()
    os.remove(path)


# ------------------------------------------------------------- live e2e


def test_equivocation_injected_via_chaos_transport_detected_live():
    """Acceptance: the chaos transport delivers a forged conflicting
    event as an extra push; the receiving node detects the fork within
    one gossip round (counter + persisted evidence), the network keeps
    committing, and the honest nodes' consensus order stays
    byte-identical."""
    n = 3
    inner = [InmemTransport(f"addr{i}", timeout=2.0) for i in range(n)]
    connect_all(inner)
    wrapped = {t.local_addr(): FaultyTransport(t, seed=3) for t in inner}
    entries = make_keyed_peers(n, addr_fn=lambda i: f"addr{i}")
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = fast_config(heartbeat=0.01)
        store = InmemStore(participants, CACHE)
        node = Node(conf, i, key, peers, store,
                    wrapped[peer.net_addr], InmemAppProxy())
        node.init()
        nodes.append(node)
    victim_key = entries[0][0]
    victim = nodes[0]
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        deadline = time.monotonic() + 90.0
        i = 0
        while time.monotonic() < deadline:
            nodes[i % n].submit_tx(f"pre tx {i}".encode())
            i += 1
            if all((nd.core.get_last_consensus_round_index() or 0) >= 1
                   for nd in nodes) and victim.core.seq >= 1:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("warmup timeout")

        with victim.core_lock:
            head, forged = _forge_at_head(victim.core, victim_key)
        wrapped[victim.local_addr].inject_equivocation(
            [forged.to_wire()])

        def fork_seen():
            return any(nd.core.forks_detected() > 0
                       for nd in nodes[1:])

        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not fork_seen():
            nodes[i % n].submit_tx(f"mid tx {i}".encode())
            i += 1
            time.sleep(0.02)
        assert fork_seen(), "equivocation was never detected"
        assert sum(f.injected["equivocate"]
                   for f in wrapped.values()) == 1
        detector = next(nd for nd in nodes[1:]
                        if nd.core.forks_detected() > 0)
        (rec,) = detector.core.fork_evidence()
        assert rec["creator"] == victim.core.hex_id()
        assert rec["index"] == head.index()
        assert rec["forged"] == forged.hex()
        # /debug/consensus surfaces it.
        health = detector.get_consensus_health()
        assert health["forks"]["detected"] >= 1
        assert health["forks"]["evidence"][0]["forged"] == forged.hex()

        # The net keeps deciding rounds after the attack...
        target = max((nd.core.get_last_consensus_round_index() or 0)
                     for nd in nodes) + 2
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            nodes[i % n].submit_tx(f"post tx {i}".encode())
            i += 1
            if all((nd.core.get_last_consensus_round_index() or 0)
                   >= target for nd in nodes):
                break
            time.sleep(0.02)
        else:
            raise AssertionError("net stopped deciding after the fork")
    finally:
        for nd in nodes:
            nd.shutdown()
    # ...and the honest order never diverged: the forged event was
    # rejected everywhere, the block streams agree, zero divergence
    # sentinel alarms.
    check_gossip(nodes)
    for nd in nodes:
        assert nd.sentinel.divergence_count() == 0, nd.sentinel.reports
