"""Socket proxy pair round-trips — reference proxy/socket_proxy_test.go:
SubmitTx flows app -> babble, CommitBlock flows babble -> app."""

from __future__ import annotations

import queue

from babble_tpu.hashgraph.block import Block
from babble_tpu.proxy import SocketAppProxy, SocketBabbleProxy


def test_socket_proxy_roundtrip():
    # babble side binds first on an ephemeral port
    app_proxy = SocketAppProxy("127.0.0.1:0", "127.0.0.1:0", timeout=1.0)
    # app side: point at the babble proxy server; bind our own server
    babble_proxy = SocketBabbleProxy(app_proxy.bind_addr, "127.0.0.1:0", timeout=1.0)
    # now tell the app proxy where the app's server actually is
    app_proxy.set_client_addr(babble_proxy.bind_addr)

    try:
        # app -> babble
        tx = b"the test transaction"
        babble_proxy.submit_tx(tx)
        got = app_proxy.submit_ch().get(timeout=1.0)
        assert got == tx

        # babble -> app
        block = Block(7, [b"tx one", b"tx two"])
        app_proxy.commit_block(block)
        got_block = babble_proxy.commit_ch().get(timeout=1.0)
        assert got_block.round_received == 7
        assert got_block.transactions == [b"tx one", b"tx two"]
        assert got_block.hash() == block.hash()

        # nil transactions survive (Go nil-slice -> null)
        app_proxy.commit_block(Block(8, None))
        got_nil = babble_proxy.commit_ch().get(timeout=1.0)
        assert got_nil.transactions is None
    finally:
        app_proxy.close()
        babble_proxy.close()


def test_dummy_client_commit_log(tmp_path):
    from babble_tpu.dummy import DummyClient

    app_proxy = SocketAppProxy("127.0.0.1:0", "127.0.0.1:0", timeout=1.0)
    log = str(tmp_path / "messages.txt")
    client = DummyClient(app_proxy.bind_addr, "127.0.0.1:0", log_path=log)
    app_proxy.set_client_addr(client.proxy.bind_addr)

    try:
        client.submit_tx(b"client1: hello")
        assert app_proxy.submit_ch().get(timeout=1.0) == b"client1: hello"

        app_proxy.commit_block(Block(0, [b"client1: hello", b"client2: hi"]))
        deadline = 50
        while len(client.state.get_committed_transactions()) < 2 and deadline:
            import time

            time.sleep(0.02)
            deadline -= 1
        assert client.state.get_committed_transactions() == [
            "client1: hello", "client2: hi",
        ]
        with open(log) as f:
            assert f.read() == "client1: hello\nclient2: hi\n"
    finally:
        client.close()
        app_proxy.close()
