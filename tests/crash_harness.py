"""Process-level kill -9 harness (docs/robustness.md "Crash recovery").

Runs N REAL nodes — `python -m babble_tpu.cli run` subprocesses over
TCP, each with a FileStore and a journal app proxy — and proves the
durable path crash-consistent: a node SIGKILLed at seeded points
mid-gossip or mid-commit, restarted with `--bootstrap`, must rejoin
and leave every node with the byte-identical block order, with zero
duplicate and zero missing application deliveries in its journal.

The harness is both a library (tests/test_crash.py drives it) and a
standalone soak:

    python tests/crash_harness.py --nodes 4 --seed 31337 --kills 2
"""

from __future__ import annotations

import json
import os
import random
import signal
import socket
import sqlite3
import subprocess
import sys
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # standalone `python tests/crash_harness.py`
    sys.path.insert(0, REPO)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class CrashNode:
    """One CLI node subprocess: datadir, FileStore, delivery journal."""

    def __init__(self, index: int, datadir: str, extra_args: List[str]):
        self.index = index
        self.datadir = datadir
        self.node_port = _free_port()
        self.service_port = _free_port()
        self.store_path = os.path.join(datadir, "store.db")
        self.journal_path = os.path.join(datadir, "journal.jsonl")
        self.extra_args = extra_args
        self.proc: Optional[subprocess.Popen] = None
        self.kills = 0

    @property
    def node_addr(self) -> str:
        return f"127.0.0.1:{self.node_port}"

    def start(self, env_extra: Optional[Dict[str, str]] = None) -> None:
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(env_extra or {})
        args = [
            sys.executable, "-m", "babble_tpu.cli", "run",
            "--datadir", self.datadir,
            "--node_addr", self.node_addr,
            "--service_addr", f"127.0.0.1:{self.service_port}",
            "--store", "file",
            "--store_path", self.store_path,
            "--journal", self.journal_path,
            "--heartbeat", "30",
            "--log_level", "error",
        ]
        if os.path.exists(self.store_path):
            args.append("--bootstrap")
        self.proc = subprocess.Popen(
            args + self.extra_args, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill9(self) -> None:
        """The real thing: SIGKILL, no cleanup, no atexit."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=30)
        self.kills += 1

    def terminate(self, timeout: float = 30.0) -> int:
        """Graceful SIGTERM shutdown (drains + commits the store)."""
        if self.proc is None:
            return 0
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=10)
            raise

    def wait_dead(self, timeout: float = 60.0) -> None:
        """Block until the process exits (self-inflicted crash points)."""
        assert self.proc is not None
        self.proc.wait(timeout=timeout)

    def stderr_tail(self) -> str:
        if self.proc is None or self.proc.stderr is None:
            return ""
        try:
            return self.proc.stderr.read().decode(errors="replace")[-2000:]
        except Exception:  # noqa: BLE001
            return ""

    # -- HTTP service ------------------------------------------------------

    def stats(self, timeout: float = 3.0) -> Dict[str, str]:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.service_port}/Stats",
                timeout=timeout) as r:
            return json.loads(r.read())

    def submit(self, tx: bytes, timeout: float = 3.0) -> None:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.service_port}/submit",
            data=tx, method="POST")
        with urllib.request.urlopen(req, timeout=timeout):
            pass

    def last_round(self) -> int:
        try:
            r = self.stats()["last_consensus_round"]
            return -1 if r == "nil" else int(r)
        except Exception:  # noqa: BLE001
            return -1

    # -- durable state (read after the process stopped) --------------------

    def block_order(self) -> List[Tuple[int, Tuple[str, ...]]]:
        """(round, tx tuple) per durable block in round order, as a
        fresh restart would see it (the same torn-tail recovery
        FileStore.load applies: blocks above the consensus anchor are
        ignored)."""
        db = sqlite3.connect(self.store_path)
        try:
            row = db.execute(
                "SELECT value FROM meta WHERE key='consensus_anchor'"
            ).fetchone()
            anchor = int(row[0]) if row else -1
            rows = db.execute(
                "SELECT rr, data FROM blocks WHERE rr <= ? ORDER BY rr",
                (anchor,)).fetchall()
        finally:
            db.close()
        import base64

        out = []
        for rr, data in rows:
            obj = json.loads(data)
            txs = tuple(base64.b64decode(t)
                        for t in (obj.get("Transactions") or []))
            out.append((rr, txs))
        return out

    def journal(self) -> List[Tuple[int, Tuple[str, ...]]]:
        """(round, tx-hex tuple) per journaled delivery, file order.
        A torn final line (killed inside the write) is skipped — it
        was not a durable delivery."""
        if not os.path.exists(self.journal_path):
            return []
        out = []
        with open(self.journal_path, "r", encoding="utf-8") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                    out.append((rec["round"], tuple(rec["txs"])))
                except (ValueError, KeyError):
                    continue
        return out


class CrashTestnet:
    """N CrashNodes with shared peers.json; seeded fault schedule."""

    def __init__(self, n: int, workdir: str, seed: int = 31337,
                 extra_args: Optional[List[str]] = None):
        self.rng = random.Random(seed)
        self.nodes: List[CrashNode] = []
        extra = extra_args or []
        for i in range(n):
            datadir = os.path.join(workdir, f"node{i}")
            os.makedirs(datadir, exist_ok=True)
            self.nodes.append(CrashNode(i, datadir, list(extra)))
        # keygen in-process (no subprocess per key): priv_key.pem +
        # one shared peers.json, the cli's startup contract.
        from babble_tpu.crypto.pem import generate_pem_key

        peers = []
        for node in self.nodes:
            dump = generate_pem_key()
            with open(os.path.join(node.datadir, "priv_key.pem"), "w") as f:
                f.write(dump.private_key)
            peers.append({"NetAddr": node.node_addr,
                          "PubKeyHex": dump.public_key})
        for node in self.nodes:
            with open(os.path.join(node.datadir, "peers.json"), "w") as f:
                json.dump(peers, f)
        self._tx_seq = 0

    # -- lifecycle ---------------------------------------------------------

    def start_all(self) -> None:
        for node in self.nodes:
            node.start()

    def wait_up(self, nodes: Optional[List[CrashNode]] = None,
                timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        for node in (nodes if nodes is not None else self.nodes):
            while True:
                if not node.alive():
                    raise AssertionError(
                        f"node {node.index} died during boot: "
                        f"{node.stderr_tail()}")
                try:
                    node.stats(timeout=1.0)
                    break
                except Exception:  # noqa: BLE001
                    if time.monotonic() > deadline:
                        raise AssertionError(
                            f"node {node.index} service never came up")
                    time.sleep(0.2)

    def shutdown_all(self) -> None:
        for node in self.nodes:
            try:
                node.terminate()
            except Exception:  # noqa: BLE001
                pass

    # -- traffic -----------------------------------------------------------

    def bombard_until(self, target_round: int, timeout: float = 120.0,
                      require: Optional[List[CrashNode]] = None) -> None:
        """Round-robin transactions into every live node until every
        node in `require` (default: all live nodes) passes
        target_round."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            live = [n for n in self.nodes if n.alive()]
            if live:
                node = live[self._tx_seq % len(live)]
                try:
                    node.submit(f"crash tx {self._tx_seq}".encode())
                except Exception:  # noqa: BLE001
                    pass  # node mid-boot or mid-kill; next tick
                self._tx_seq += 1
            goal = require if require is not None else live
            if goal and all(n.last_round() >= target_round for n in goal):
                return
            time.sleep(0.03)
        rounds = [(n.index, n.last_round()) for n in self.nodes]
        raise AssertionError(
            f"timeout: rounds {rounds} never reached {target_round}")

    def max_round(self) -> int:
        return max((n.last_round() for n in self.nodes if n.alive()),
                   default=-1)

    def assert_no_divergence_alarms(self) -> None:
        """Live audit of the divergence sentinel (docs/observability.md
        "Consensus health"): after all the kill -9 / restart churn, no
        node may have flagged a peer's committed-block chain — the
        sentinel's false-positive bar under real crash recovery."""
        for node in self.nodes:
            if not node.alive():
                continue
            try:
                stats = node.stats()
            except Exception:  # noqa: BLE001 - mid-shutdown
                continue
            assert int(stats.get("divergences", "0")) == 0, (
                f"node {node.index} raised divergence alarms: "
                f"{stats.get('divergences')}")

    # -- the acceptance invariants -----------------------------------------

    def assert_invariants(self) -> Dict[str, int]:
        """All processes must be stopped. Asserts:
        1. byte-identical blocks across all nodes on every round two
           stores share (a fast-forwarded store's floor may sit above
           round 0 — pre-frame history is legitimately absent there);
        2. every journal has strictly increasing rounds (zero
           duplicate deliveries);
        3. every tx-bearing durable block between a node's store floor
           and its journal tail is journaled exactly once, with the
           exact block transactions (zero missing deliveries)."""
        orders = {n.index: n.block_order() for n in self.nodes}
        min_blocks = min(len(o) for o in orders.values())
        assert min_blocks > 0, f"no committed blocks: { {k: len(v) for k, v in orders.items()} }"
        by_round = {n.index: dict(orders[n.index]) for n in self.nodes}
        ref = by_round[self.nodes[0].index]
        shared_total = 0
        for node in self.nodes[1:]:
            got = by_round[node.index]
            shared = set(ref) & set(got)
            if not shared:
                # Legitimate only when the round RANGES are disjoint —
                # a fast-forwarded store's floor can sit above another
                # node's ceiling at stop time. Overlapping ranges with
                # no common block round would be a divergence.
                assert (min(ref) > max(got) or min(got) > max(ref)), (
                    f"nodes 0/{node.index} overlap in rounds but share "
                    f"no committed block")
            shared_total += len(shared)
            for rr in shared:
                assert got[rr] == ref[rr], (
                    f"block {rr} diverged on node {node.index}")

        deliveries = 0
        for node in self.nodes:
            journal = node.journal()
            rounds = [rr for rr, _ in journal]
            assert rounds == sorted(set(rounds)), (
                f"node {node.index}: duplicate/unordered deliveries "
                f"{rounds}")
            deliveries += len(journal)
            if not journal or not orders[node.index]:
                continue
            tail = rounds[-1]
            floor = orders[node.index][0][0]
            # Only tx-bearing blocks are delivered to the app; empty
            # blocks are stored but never emitted (find_order).
            want = [(rr, txs) for rr, txs in orders[node.index]
                    if txs and rr <= tail]
            got = [(rr, tuple(bytes.fromhex(t) for t in txs))
                   for rr, txs in journal if rr >= floor]
            assert got == want, (
                f"node {node.index}: journal disagrees with durable "
                f"blocks\n  journal: {got[-5:]}\n  store:   {want[-5:]}")
        return {"blocks": min_blocks, "deliveries": deliveries,
                "shared_rounds": shared_total}


def run_soak(workdir: str, n: int = 4, seed: int = 31337, kills: int = 2,
             log=print) -> Dict[str, int]:
    """The full seeded soak: boot, converge, then `kills` cycles of
    [SIGKILL a random node at a seeded moment mid-traffic, advance the
    survivors, restart the victim with --bootstrap, reconverge], then a
    graceful stop and the invariant audit."""
    net = CrashTestnet(n, workdir, seed=seed)
    try:
        net.start_all()
        net.wait_up()
        net.bombard_until(target_round=2, timeout=240.0)

        for cycle in range(kills):
            victim = net.rng.choice(net.nodes)
            # Seeded kill moment: traffic keeps flowing while we wait,
            # so the SIGKILL lands mid-gossip / mid-commit, not in a
            # quiet net.
            fuse = net.rng.uniform(0.2, 1.0)
            t_end = time.monotonic() + fuse
            while time.monotonic() < t_end:
                try:
                    victim.submit(f"fuse tx {net._tx_seq}".encode())
                    net._tx_seq += 1
                except Exception:  # noqa: BLE001
                    pass
                time.sleep(0.01)
            log(f"[cycle {cycle}] SIGKILL node {victim.index} "
                f"(fuse {fuse:.2f}s, round {net.max_round()})")
            victim.kill9()

            survivors = [x for x in net.nodes if x is not victim]
            net.bombard_until(target_round=net.max_round() + 2,
                              timeout=240.0, require=survivors)

            log(f"[cycle {cycle}] restart node {victim.index} "
                f"with --bootstrap")
            victim.start()
            net.wait_up([victim])
            net.bombard_until(target_round=net.max_round() + 1,
                              timeout=300.0)

        final = net.max_round() + 2
        net.bombard_until(target_round=final, timeout=300.0)
        net.assert_no_divergence_alarms()
        log(f"graceful stop at round >= {final}")
    finally:
        net.shutdown_all()
    result = net.assert_invariants()
    log(f"soak OK: {result}")
    return result


if __name__ == "__main__":
    import argparse
    import tempfile

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--seed", type=int, default=31337)
    ap.add_argument("--kills", type=int, default=2)
    ap.add_argument("--workdir", default="")
    opts = ap.parse_args()
    wd = opts.workdir or tempfile.mkdtemp(prefix="babble-crash-")
    print(f"workdir: {wd}")
    run_soak(wd, n=opts.nodes, seed=opts.seed, kills=opts.kills)
