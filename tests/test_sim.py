"""Batched per-peer view simulation: the device-side checkGossip.

- Each simulated peer's ancestry-closed view runs through the masked
  pipeline in one vmap; all views must produce prefix-compatible
  consensus orders (reference node/node_test.go:548-599).
- A single peer's masked view must match the incremental host engine
  fed exactly that sub-DAG — masked-kernel parity."""

from __future__ import annotations

import numpy as np

from babble_tpu.hashgraph import Hashgraph, InmemStore
from babble_tpu.ops.sim import (
    GossipSim,
    check_view_consistency,
    consensus_views,
    view_order,
)


def build_sim(n=5, steps=150, seed=3):
    sim = GossipSim(n, seed=seed)
    sim.run(steps)
    return sim


def test_view_consistency_vmap():
    sim = build_sim()
    dag = sim.dag()
    masks = sim.view_masks()
    # add the full view as an extra row: every peer's order must be a
    # prefix-compatible subsequence of the global order too
    masks = np.vstack([masks, np.ones((1, dag.e), dtype=bool)])
    out = consensus_views(dag, masks)
    rr_v = np.asarray(out[4])
    cts_v = np.asarray(out[5])
    orders = check_view_consistency(dag, rr_v, cts_v)
    assert len(orders[-1]) > 0, "full view reached no consensus"
    # at least one partial view decided something
    assert any(len(o) > 0 for o in orders[:-1])


def test_masked_view_matches_host_engine():
    sim = build_sim(n=5, steps=120, seed=11)
    dag = sim.dag()
    masks = sim.view_masks()
    # pick the best-informed peer's view
    v = int(masks.sum(1).argmax())
    mask = masks[v]

    # host engine over exactly that sub-DAG, in insertion order
    sub_events = [ev for i, ev in enumerate(sim.events) if mask[i]]
    import json
    from babble_tpu.hashgraph.event import event_from_json_obj

    h = Hashgraph(sim.participants, InmemStore(sim.participants, 10000))
    for ev in sub_events:
        h.insert_event(event_from_json_obj(json.loads(ev.marshal())), True)
    h.run_consensus()
    host_order = h.consensus_events()

    out = consensus_views(dag, mask[None, :])
    rr = np.asarray(out[4])[0]
    cts = np.asarray(out[5])[0]
    dev_order = [dag.hexes[i] for i in view_order(dag, rr, cts)]
    assert dev_order == host_order, "masked view diverges from host engine"

    # per-event round parity within the view
    rounds = np.asarray(out[0])[0]
    for i, ev in enumerate(sim.events):
        if mask[i]:
            assert int(rounds[i]) == h.round(ev.hex())


# ---------------------------------------------------------------- at scale


def test_views_at_scale_factored():
    """64 ancestry-closed views (16 peers x 4 temporal snapshots)
    through the factored vmap (shared coordinates, per-view witness
    stages) with a power-law selector — the at-scale
    check_view_consistency target. Temporal snapshots also assert
    order monotonicity: a peer's earlier consensus order must be a
    prefix of its later one."""
    from babble_tpu.ops.sim import (
        check_view_consistency,
        consensus_views_factored,
        simulate_views,
    )

    n = 16
    dag, masks, s_rank = simulate_views(
        n, steps=800, selector="powerlaw", alpha=1.2, seed=5,
        snapshots=[200, 400, 600, 800])
    assert masks.shape[0] == 64
    out = consensus_views_factored(dag, masks)
    rr_v = np.asarray(out[4])
    cts_v = np.asarray(out[5])
    orders = check_view_consistency(dag, rr_v, cts_v, s_ints=s_rank)
    decided = [len(o) for o in orders]
    assert max(decided) > 100, f"too little consensus at scale: {decided}"


def test_views_with_silent_peers():
    """Up to n - supermajority peers can be silent (the missing-node
    scenario, reference node_test.go:409-420) and the remaining
    supermajority still reaches prefix-consistent consensus."""
    from babble_tpu.ops.sim import (
        check_view_consistency,
        consensus_views_factored,
        simulate_views,
    )

    n = 16
    sm = 2 * n // 3 + 1
    silent = np.zeros(n, bool)
    silent[sm:] = True  # n - sm = 5 silent peers
    dag, masks, s_rank = simulate_views(
        n, steps=400, silent=silent, seed=6)
    out = consensus_views_factored(dag, masks[~silent])
    rr_v = np.asarray(out[4])
    cts_v = np.asarray(out[5])
    orders = check_view_consistency(dag, rr_v, cts_v, s_ints=s_rank)
    assert max(len(o) for o in orders) > 50, "silent-peer run decided too little"
    # silent peers' initial events are invisible to the active network
    for sid in np.nonzero(silent)[0]:
        assert not masks[~silent][:, sid].any()


def test_factored_views_match_fused():
    """The factored path (shared coordinates) must equal the fused
    per-view pipeline bit-for-bit."""
    from babble_tpu.ops.sim import consensus_views_factored

    sim = build_sim(n=5, steps=100, seed=9)
    dag = sim.dag()
    masks = sim.view_masks()
    a = consensus_views(dag, masks)
    b = consensus_views_factored(dag, masks)
    for name, x, y in zip(
        ("rounds", "wit", "wt", "famous", "rr", "cts"), a, b
    ):
        assert (np.asarray(x) == np.asarray(y)).all(), name
