"""Multi-chip sharding smoke tests on the virtual 8-device CPU mesh
(provisioned by conftest.py)."""

import jax
import pytest


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out[0].shape == (256,)
