"""Cross-validation of the block-closure / round-frontier kernels
against the depth-sequential wavefront kernels (which are themselves
parity-tested against the host engine on the reference fixtures).

Reference semantics anchors: hashgraph.go:448-499 (coordinates),
211-339 + 616-646 (rounds/witnesses)."""

import numpy as np
import pytest

from babble_tpu.ops import closure, frontier, kernels
from babble_tpu.ops.dag import synthetic_dag
from babble_tpu.ops.pipeline import run_pipeline, run_pipeline_wavefront


def _wavefront(dag):
    n, sm, r = dag.n, dag.super_majority, dag.max_rounds
    la = kernels.compute_last_ancestors(
        dag.self_parent, dag.other_parent, dag.creator, dag.index,
        dag.levels, n=n)
    fd = kernels.compute_first_descendants(
        np.asarray(la), dag.creator, dag.index, dag.chain, dag.chain_len,
        n=n)
    rounds, wit, wt = kernels.compute_rounds(
        dag.self_parent, dag.other_parent, dag.creator, dag.index,
        la, fd, dag.levels, dag.root_round, n=n, sm=sm, r=r)
    return (np.asarray(la), np.asarray(fd), np.asarray(rounds),
            np.asarray(wit), np.asarray(wt))


def _frontier(dag, block=128, rc=16):
    n, sm = dag.n, dag.super_majority
    la, rbase = closure.coordinates(dag, block=block)
    fd = kernels.compute_first_descendants(
        la, dag.creator, dag.index, dag.chain, dag.chain_len, n=n)
    wt, fr_rel, rho_min = frontier.compute_frontier(
        la, rbase, fd, dag.chain, dag.chain_len, dag.root_round,
        n=n, sm=sm, rc=rc)
    e = dag.e
    rounds, wit = frontier.rounds_from_frontier(
        fr_rel, dag.creator[:e], dag.index[:e], dag.self_parent[:e],
        rho_min, n=n)
    return (np.asarray(la), np.asarray(rbase), np.asarray(rounds),
            np.asarray(wit), wt)


@pytest.mark.parametrize(
    "n,e,seed", [(4, 60, 0), (8, 300, 1), (16, 1200, 2), (32, 2500, 3)]
)
def test_parity_random_gossip(n, e, seed):
    dag, _ = synthetic_dag(n, e, seed=seed)
    la_o, fd_o, rounds_o, wit_o, wt_o = _wavefront(dag)
    la_n, rbase, rounds_n, wit_n, wt_n = _frontier(dag)
    assert (la_n == la_o).all()
    assert (rounds_n == rounds_o).all()
    assert (wit_n == wit_o).all()
    rmax = int(rounds_o.max())
    assert (wt_n[: rmax + 1] == wt_o[: rmax + 1]).all()


def test_parity_nonbase_roots():
    """Non-base root rounds (the Reset / start-from-the-middle path,
    reference hashgraph.go:879-898): rbase must seed frontiers above
    round 0 and the skip-correction must hold candidates back until
    their true round."""
    n, e = 6, 150
    dag, _ = synthetic_dag(n, e, seed=5)
    # Pretend this DAG restarts from mixed per-participant root rounds.
    dag.root_round = np.array([3, 4, 3, 5, 4, 3], dtype=np.int32)
    la_o, fd_o, rounds_o, wit_o, wt_o = _wavefront(dag)
    la_n, rbase, rounds_n, wit_n, wt_n = _frontier(dag)
    assert (rounds_n == rounds_o).all()
    assert (wit_n == wit_o).all()
    rmax = int(rounds_o.max())
    assert rmax >= 6  # actually started above base
    assert (wt_n[: rmax + 1] == wt_o[: rmax + 1]).all()


def test_pipeline_matches_wavefront_pipeline():
    """Full-pipeline equivalence (fame, round-received, timestamps).
    engine='closure' is forced — on CPU the 'auto' default resolves to
    the wavefront, which would compare the oracle against itself."""
    dag, _ = synthetic_dag(8, 400, seed=7)
    out_n = run_pipeline(dag, engine="closure")
    out_o = run_pipeline_wavefront(dag)
    names = ["rounds", "wit", "wt", "famous", "rr", "cts"]
    for name, a, b in zip(names, out_n, out_o):
        a, b = np.asarray(a), np.asarray(b)
        if name in ("wt", "famous"):
            r = min(a.shape[0], b.shape[0])
            rmax = int(np.asarray(out_o[0]).max()) + 1
            r = min(r, rmax)
            assert (a[:r] == b[:r]).all(), name
        else:
            assert (a == b).all(), name


def test_closure_block_sizes_agree():
    """Block size must not affect results (pure scheduling knob)."""
    dag, _ = synthetic_dag(8, 300, seed=9)
    la64, rb64 = closure.coordinates(dag, block=64)
    la256, rb256 = closure.coordinates(dag, block=256)
    assert (np.asarray(la64) == np.asarray(la256)).all()
    assert (np.asarray(rb64) == np.asarray(rb256)).all()
