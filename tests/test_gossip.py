"""Gossip efficiency observatory (docs/observability.md "Gossip
efficiency").

Covers the measurement plane end to end:

- `Core.sync` classifies every offered event as new / duplicate /
  stale-window and returns the counts;
- self-events carry the cluster-epoch creation stamp, which rides both
  wire codecs as the `_CreateNs` sidecar — absent ⇒ byte-identical
  legacy and columnar forms (pinned like `_TraceID`), present ⇒
  round-trips through both and mixed-format clusters still commit
  byte-identical blocks;
- propagation latency (create -> remote insert) lands in the
  per-node histogram;
- the Node attributes classifications per (peer, leg), `/debug/gossip`
  renders the efficiency table, `/debug/peers` gains the redundancy
  columns, and `FaultyTransport`'s duplicate-push injection shows up
  in `babble_gossip_duplicate_events_total` (the loop between fault
  injection and the accounting);
- `bench_compare`'s soak shape extension gates redundancy ratios
  un-normalized.
"""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

import babble_tpu.gojson as gojson
from babble_tpu import crypto
from babble_tpu.gojson import Timestamp
from babble_tpu.hashgraph.event import WireBody, WireEvent
from babble_tpu.hashgraph.inmem_store import InmemStore
from babble_tpu.net import FaultyTransport, InmemTransport
from babble_tpu.net.columnar import ColumnarEvents, wire_payload_nbytes
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.node import Node
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.node.core import Core
from babble_tpu.proxy import InmemAppProxy
from babble_tpu.telemetry import ClusterClock

from test_node import check_gossip, make_keyed_peers

CACHE = 10000


def _three_cores(clock=False, seed_base=7300):
    keys = sorted((crypto.key_from_seed(seed_base + i) for i in range(3)),
                  key=lambda k: crypto.pub_key_bytes(k).hex().upper())
    parts = {"0x" + crypto.pub_key_bytes(k).hex().upper(): i
             for i, k in enumerate(keys)}
    cores = [Core(i, k, parts, InmemStore(parts, CACHE),
                  clock=ClusterClock() if clock else None)
             for i, k in enumerate(keys)]
    for c in cores:
        c.init()
    return cores


def _wire_event(create_ns=0, trace_id=0, txs=(b"tx",), idx=1):
    return WireEvent(
        WireBody(
            transactions=list(txs),
            self_parent_index=idx - 1,
            other_parent_creator_id=1,
            other_parent_index=0,
            creator_id=0,
            timestamp=Timestamp(1_700_000_000_000_000_123),
            index=idx,
        ),
        r=12345, s=67890, trace_id=trace_id, create_ns=create_ns)


# -------------------------------------------------- sync classification


def test_sync_classifies_new_then_duplicate():
    a, b, _ = _three_cores()
    diff = a.diff(b.known())
    payload = a.to_wire_batch(diff, "columnar")
    stats = b.sync(payload)
    assert stats["offered"] == len(diff)
    assert stats["new"] == len(diff)
    assert stats["duplicate"] == 0 and stats["stale"] == 0
    # The same payload again: every offered event is now a duplicate.
    stats = b.sync(a.to_wire_batch(diff, "columnar"))
    assert stats["offered"] == len(diff)
    assert stats["new"] == 0
    assert stats["duplicate"] == len(diff)


def test_sync_classification_matches_on_legacy_payloads():
    a, b, _ = _three_cores()
    diff = a.diff(b.known())
    stats = b.sync(a.to_wire_batch(diff, "gojson"))
    assert stats == {"offered": len(diff), "new": len(diff),
                     "duplicate": 0, "stale": 0}
    stats = b.sync(a.to_wire_batch(diff, "gojson"))
    assert stats["duplicate"] == len(diff) and stats["new"] == 0


# ------------------------------------------------- creation-stamp sidecar


def test_self_events_carry_cluster_epoch_stamp():
    (a,) = _three_cores(clock=True)[:1]
    head = a.get_head()
    assert head.create_ns > 0
    w = head.to_wire()
    assert w.create_ns == head.create_ns
    assert w.to_dict()["_CreateNs"] == head.create_ns


def test_bare_core_never_stamps():
    (a,) = _three_cores()[:1]
    assert a.get_head().create_ns == 0
    assert "_CreateNs" not in a.get_head().to_wire().to_dict()


def test_sidecar_absent_is_byte_identical_both_codecs():
    plain = [_wire_event(), _wire_event(idx=2)]
    # legacy Go-JSON: no sidecar key at all when unstamped
    d = plain[0].to_dict()
    assert "_CreateNs" not in d and "_TraceID" not in d
    # columnar: no column, no flag bit, frame grows by exactly 8n when
    # the stamp appears (pinned like the trace column)
    buf = ColumnarEvents.from_wire_events(plain).encode()
    assert buf[8] & 2 == 0  # flags byte: create column absent
    stamped = [_wire_event(create_ns=123456789),
               _wire_event(idx=2)]
    sbuf = ColumnarEvents.from_wire_events(stamped).encode()
    assert sbuf[8] & 2 == 2
    assert len(sbuf) == len(buf) + 2 * 8


def test_sidecar_round_trips_both_codecs():
    w = _wire_event(create_ns=1_723_400_000_123_456_789, trace_id=7)
    # columnar
    cols = ColumnarEvents.decode(
        ColumnarEvents.from_wire_events([w]).encode())
    back = cols.to_wire_events()[0]
    assert back.create_ns == w.create_ns
    assert back.trace_id == w.trace_id
    assert back.to_dict() == w.to_dict()
    # gojson (through real JSON bytes, like the TCP relay)
    w2 = WireEvent.from_json_obj(json.loads(
        json.dumps(w.to_dict(), default=_b64)))
    assert w2.create_ns == w.create_ns
    assert w2.to_dict() == w.to_dict()


def _b64(obj):
    import base64

    if isinstance(obj, (bytes, bytearray)):
        return base64.b64encode(bytes(obj)).decode()
    raise TypeError


def test_payload_nbytes_columnar_is_exact():
    cols = ColumnarEvents.from_wire_events(
        [_wire_event(create_ns=5, trace_id=9),
         _wire_event(idx=2, txs=(b"abc", b""))])
    assert cols.nbytes() == len(cols.encode())
    # legacy estimate: positive and roughly envelope-sized
    est = wire_payload_nbytes([_wire_event()])
    assert 200 < est < 600


def test_mixed_stamped_cluster_commits_byte_identical_blocks(monkeypatch):
    """Stamped vs unstamped, columnar vs legacy, any mix: consensus
    output is byte-identical — the sidecar never leaks into the DAG.
    Propagation latency is observed on the stamped runs."""
    tick = {"ns": 1_700_000_000_000_000_000}

    def fake_now():
        tick["ns"] += 1_000_000
        return Timestamp(tick["ns"])

    monkeypatch.setattr(gojson.Timestamp, "now", staticmethod(fake_now))

    def run(wire_formats, clock):
        tick["ns"] = 1_700_000_000_000_000_000
        cores = _three_cores(clock=clock)
        before = sum(c._m_propagation.count for c in cores
                     if c._m_propagation is not None)
        blocks = [[] for _ in cores]
        for i, c in enumerate(cores):
            c._commit_callback = blocks[i].append
            c.hg.commit_callback = blocks[i].append
        script = [(0, 1), (1, 2), (2, 0), (1, 0), (0, 2), (2, 1)] * 10
        for i, (dst, src) in enumerate(script):
            diff = cores[src].diff(cores[dst].known())
            payload = cores[src].to_wire_batch(diff, wire_formats[src])
            cores[dst].add_transactions([b"tx %d" % i])
            cores[dst].sync(payload)
            cores[dst].run_consensus()
        out = []
        for blist in blocks:
            out.append([json.dumps(
                {"r": b.round_received,
                 "txs": [t.hex() for t in (b.transactions or [])]},
                sort_keys=True) for b in blist])
        prop = sum(c._m_propagation.count for c in cores
                   if c._m_propagation is not None) - before
        return out, prop

    unstamped, p0 = run(["columnar"] * 3, clock=False)
    stamped_col, p1 = run(["columnar"] * 3, clock=True)
    stamped_mix, p2 = run(["columnar", "gojson", "columnar"], clock=True)
    assert unstamped == stamped_col == stamped_mix
    assert p0 == 0  # no clocks, no stamps, no samples
    assert p1 > 0 and p2 > 0  # stamped runs observed real latencies


# ------------------------------------------------------- live node plane


def _make_net(n=3, heartbeat=0.01, observatory=True, **faults):
    inner = [InmemTransport(f"addr{i}", timeout=2.0) for i in range(n)]
    connect_all(inner)
    if faults:
        trans = {t.local_addr(): FaultyTransport(t, seed=11, **faults)
                 for t in inner}
    else:
        trans = {t.local_addr(): t for t in inner}
    entries = make_keyed_peers(n, addr_fn=lambda i: f"addr{i}")
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = fast_config(heartbeat=heartbeat)
        conf.gossip_observatory = observatory
        store = InmemStore(participants, CACHE)
        nodes.append(Node(conf, i, key, peers, store,
                          trans[peer.net_addr], InmemAppProxy()))
        nodes[-1].init()
    return nodes


def _run_until_round(nodes, target_round=3, timeout=60.0):
    for nd in nodes:
        nd.run_async(gossip=True)
    deadline = time.monotonic() + timeout
    i = 0
    while time.monotonic() < deadline:
        nodes[i % len(nodes)].submit_tx(b"gtx %d" % i)
        i += 1
        if all((nd.core.get_last_consensus_round_index() or 0)
               >= target_round for nd in nodes):
            return
        time.sleep(0.02)
    raise AssertionError("net never reached the target round")


def test_node_accounting_and_debug_endpoints():
    from babble_tpu.service import Service
    from babble_tpu.telemetry import promtext

    nodes = _make_net()
    svc = Service("127.0.0.1:0", nodes[0])
    svc.serve_async()
    try:
        _run_until_round(nodes)
        nd = nodes[0]
        agg = {k: c.value for k, c in nd._m_gossip_agg.items()}
        assert agg["offered"] > 0 and agg["new"] > 0
        assert agg["syncs"] > 0 and agg["bytes"] > 0
        # classification identity: every offered event lands in
        # exactly one bucket
        assert agg["offered"] == agg["new"] + agg["duplicate"] \
            + agg["stale"]
        # propagation latency observed for remote stamped events
        assert nd.core._m_propagation.count > 0

        # /debug/gossip: efficiency table with per-peer legs + totals
        with urllib.request.urlopen(
                f"http://{svc.addr}/debug/gossip", timeout=10) as r:
            gdbg = json.loads(r.read())
        # >= not ==: the endpoint reads LIVE counters, and in-flight
        # relays may land between the snapshot above and this scrape.
        assert gdbg["totals"]["offered"] >= int(agg["offered"])
        assert gdbg["peers"]
        peer, legs = next(iter(gdbg["peers"].items()))
        assert "totals" in legs
        assert "redundancy_ratio" in legs["totals"]
        assert "bytes_per_new_event" in legs["totals"]
        assert "propagation_ms" in gdbg
        assert gdbg["known_bookkeeping"]["calls"] > 0

        # /debug/peers: the efficiency columns joined onto peer health
        with urllib.request.urlopen(
                f"http://{svc.addr}/debug/peers", timeout=10) as r:
            pdbg = json.loads(r.read())
        row = next(iter(pdbg["peers"].values()))
        assert "redundancy_ratio" in row
        assert "bytes_per_new_event" in row

        # /metrics: the families a Prometheus scrape must see
        with urllib.request.urlopen(
                f"http://{svc.addr}/metrics", timeout=10) as r:
            samples, _ = promtext.parse(r.read().decode())
        for fam in ("babble_gossip_offered_events_total",
                    "babble_gossip_new_events_total",
                    "babble_gossip_duplicate_events_total",
                    "babble_gossip_syncs_total",
                    "babble_gossip_payload_bytes_total",
                    "babble_propagation_latency_seconds"):
            assert any(fam in s for s in samples), fam
        # per-peer children carry peer+leg labels (the plumtree legs
        # since the epidemic-broadcast PR — docs/gossip.md; the legacy
        # pull/push_in names survive under --no_plumtree)
        labeled = [lb for lb, v in
                   samples["babble_gossip_offered_events_total"]
                   if "peer" in lb]
        assert any(lb.get("leg") in ("eager", "ihave", "graft",
                                     "lazy_pull", "pull", "push_in")
                   for lb in labeled)
    finally:
        for nd in nodes:
            nd.shutdown()
        svc.close()
    check_gossip(nodes)


def test_duplicate_push_injection_feeds_duplicate_counter():
    """Satellite: the chaos transport's at-least-once duplicate
    delivery must be VISIBLE in the new accounting — every injected
    duplicate push re-offers an already-present batch."""
    nodes = _make_net(duplicate=1.0)
    try:
        _run_until_round(nodes, target_round=2)
    finally:
        for nd in nodes:
            nd.shutdown()
    injected = sum(nd.trans.injected["duplicate"] for nd in nodes)
    assert injected > 0
    dup = sum(nd._m_gossip_agg["duplicate"].value for nd in nodes)
    assert dup > 0, "injected duplicate pushes never hit the counter"
    # and specifically on an inbound-push leg of some node ("eager"
    # since the epidemic-broadcast PR; "push_in" under --no_plumtree)
    push_dup = sum(
        ch["duplicate"].value
        for nd in nodes
        for (peer, leg), ch in nd._gossip_children.items()
        if leg in ("eager", "push_in"))
    assert push_dup > 0


def test_observatory_off_disables_everything():
    nodes = _make_net(observatory=False)
    try:
        _run_until_round(nodes, target_round=2)
        nd = nodes[0]
        assert nd._m_gossip_agg == {}
        assert nd._gossip_children == {}
        assert nd.get_gossip_stats() == {"enabled": False}
        assert nd.gossip_peer_efficiency() == {}
        assert nd.core._m_propagation is None
        # no stamps ⇒ the wire form stays byte-identical to legacy
        head = nd.core.get_head()
        assert head.create_ns == 0
        assert "_CreateNs" not in head.to_wire().to_dict()
        # and the known phase timer never ran
        assert "known" not in nd.core.phase_ns
    finally:
        for nd in nodes:
            nd.shutdown()
    check_gossip(nodes)


# ------------------------------------------------- bench_compare shapes


def test_bench_compare_gates_soak_ratio_unnormalized():
    import bench_compare as bc

    base = {"metric": "gossip_soak", "host_events_per_s": 1000.0,
            "soak16_events_per_s": 100.0,
            "soak16_redundancy_ratio": 2.0,
            "soak16_propagation_p99_ms": 50.0}
    # Fresh runner is 2x faster — the ratio must NOT be scaled by the
    # yardstick, so a 50% redundancy jump is a regression even though
    # every throughput number doubled.
    fresh = {"metric": "gossip_soak", "host_events_per_s": 2000.0,
             "soak16_events_per_s": 200.0,
             "soak16_redundancy_ratio": 3.0,
             "soak16_propagation_p99_ms": 25.0}
    rows = {r["key"]: r for r in bc.compare(fresh, base, 0.10)}
    assert rows["soak16_events_per_s"]["status"] in ("ok", "improved")
    assert rows["soak16_redundancy_ratio"]["status"] == "REGRESSION"
    assert rows["soak16_redundancy_ratio"]["expected"] == 2.0
    # improvement never fails
    fresh["soak16_redundancy_ratio"] = 1.5
    rows = {r["key"]: r for r in bc.compare(fresh, base, 0.10)}
    assert rows["soak16_redundancy_ratio"]["status"] == "improved"
    # info kinds never gate
    base["soak16_coverage_ms"] = 10.0
    fresh["soak16_coverage_ms"] = 500.0
    rows = {r["key"]: r for r in bc.compare(fresh, base, 0.10)}
    assert rows["soak16_coverage_ms"]["status"] == "info"
