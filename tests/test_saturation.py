"""Saturation observatory tests (docs/observability.md "Saturation"):
queue/backpressure accounting semantics, the in-process flame
profiler, thread CPU attribution in a live scrape, and the
bottleneck-by-name acceptance — a saturated 3-node net must show the
stalled queue's wait p99 exceeding every other queue's, with its
depth riding capacity, asserted against a real /metrics scrape."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from babble_tpu.hashgraph import InmemStore
from babble_tpu.net import InmemTransport
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.node import Node
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.proxy import InmemAppProxy
from babble_tpu.service import Service
from babble_tpu.telemetry import (InstrumentedQueue, QueueInstrument,
                                  Registry, profiler, promtext)

from test_node import CACHE, make_keyed_peers, make_nodes, run_gossip

SATURATION_FAMILIES = [
    "babble_queue_depth",
    "babble_queue_capacity",
    "babble_queue_wait_seconds",
    "babble_queue_dropped_total",
    "babble_thread_cpu_seconds_total",
    "babble_cpu_utilization_cores",
    "babble_cpu_saturation_ratio",
]


# ------------------------------------------------- queue accounting


def test_instrumented_queue_depth_wait_overflow():
    """The commit_ch shape: a bounded InstrumentedQueue exports depth
    and capacity gauges, observes enqueue->dequeue wait, and counts
    overflow drops instead of raising."""
    reg = Registry()
    inst = QueueInstrument(reg, "commit", 2, node="t")
    q = InstrumentedQueue(2, inst)
    q.put("a")
    q.put("b")
    snap = inst.snapshot()
    assert snap["depth"] == 2
    assert snap["capacity"] == 2
    assert snap["waits"] == 0  # nothing dequeued yet

    # Overflow: put_drop on a full queue records a drop, never blocks.
    assert q.put_drop("c") is False
    assert inst.snapshot()["dropped"] == 1

    time.sleep(0.05)
    assert q.get() == "a"  # FIFO preserved through the wrapping
    snap = inst.snapshot()
    assert snap["depth"] == 1
    assert snap["waits"] == 1
    # The item sat for at least the sleep above.
    assert snap["wait_p99_ms"] >= 40.0

    text = reg.render()
    for fam in ("babble_queue_depth", "babble_queue_capacity",
                "babble_queue_wait_seconds",
                "babble_queue_dropped_total"):
        assert fam in text, fam
    samples, _ = promtext.parse(text)
    depth = [v for lb, v in samples["babble_queue_depth"]
             if lb.get("queue") == "commit"]
    assert depth == [1.0]


def test_instrumented_queue_unbounded_capacity_zero():
    """Capacity 0 is the unbounded marker (the verify pool's pending
    queue) — depth still reads, nothing ever drops."""
    reg = Registry()
    inst = QueueInstrument(reg, "verify_pool", 0)
    q = InstrumentedQueue(0, inst)
    for i in range(100):
        q.put(i)
    snap = inst.snapshot()
    assert snap["depth"] == 100
    assert snap["capacity"] == 0
    assert snap["dropped"] == 0


def test_verify_pool_cancelled_chunks_keep_wait_accounting(monkeypatch):
    """Shutdown-drain instrument gap (ISSUE 16 satellite): chunks
    cancelled between submit and pickup (the shared pool is replaced
    with `shutdown(wait=False)` when it grows) must NOT vanish from
    `babble_queue_wait_seconds` — verify_events observes their queued
    wait, counts them as drops, and verifies them inline so the memos
    still land."""
    from concurrent.futures import Future

    from babble_tpu import crypto
    from babble_tpu.hashgraph.event import Event
    from babble_tpu.node import ingest

    key = crypto.key_from_seed(321)
    pub = crypto.pub_key_bytes(key)
    events = []
    for i in range(16):
        ev = Event.new([b"sat-%d" % i], ["p0", "p1"], pub, i)
        ev.sign(key)
        ev._sig_ok = None  # drop sign()'s memo: force real verification
        events.append(ev)
    events[3].r = int(events[3].r) ^ 1  # one bad memo expected

    class CancellingPool:
        def submit(self, fn, *args):
            f = Future()
            f.cancel()  # never picked up: the shutdown-drain shape
            return f

    monkeypatch.setattr(ingest, "_get_pool",
                        lambda workers: CancellingPool())
    inst = ingest._pool_instrument()
    before = inst.snapshot()

    ingest.verify_events(events, workers=4)

    after = inst.snapshot()
    n_chunks = 4  # 16 events / 4 workers
    assert after["waits"] == before["waits"] + n_chunks
    assert after["dropped"] == before["dropped"] + n_chunks
    # The cancelled chunks were still verified (inline fallback).
    verdicts = [ev._sig_ok for ev in events]
    assert verdicts == [True] * 3 + [False] + [True] * 12


def test_verify_pool_killed_process_keeps_wait_accounting():
    """The procs-runtime twin of the cancelled-chunk contract (ISSUE
    18 satellite): a worker PROCESS killed with a chunk in flight must
    observe the chunk's queued wait on the same verify_pool
    instrument, count a drop, and re-verify inline — then the
    supervisor respawns the worker."""
    import os as _os
    import signal as _signal

    from babble_tpu import crypto
    from babble_tpu.hashgraph.event import Event
    from babble_tpu.node import ingest, runtime as rt

    if not hasattr(_os, "sched_getaffinity"):
        pytest.skip("procs runtime targets Linux schedulers")
    rt.reset_for_tests()
    try:
        key = crypto.key_from_seed(654)
        pub = crypto.pub_key_bytes(key)
        events = []
        for i in range(16):
            ev = Event.new([b"kill-%d" % i], ["p0", "p1"], pub, i)
            ev.sign(key)
            ev._sig_ok = None
            events.append(ev)
        events[3].r = int(events[3].r) ^ 1

        pool = rt.get_pool(2)
        workers = pool.workers()
        _os.kill(workers[0].proc.pid, _signal.SIGKILL)
        workers[0].proc.join(timeout=5.0)
        # Pin the dead worker in place for this dispatch (the
        # supervisor would otherwise respawn it BEFORE the send, and
        # the chunk would never be in flight on a corpse).
        pool._ensure = \
            lambda i, count_restart=True: pool._workers[i % pool.size]

        inst = ingest._pool_instrument()
        before = inst.snapshot()
        ingest.verify_events(events, workers=2, runtime="procs")
        after = inst.snapshot()

        assert after["dropped"] == before["dropped"] + 1
        assert after["waits"] >= before["waits"] + 2
        verdicts = [ev._sig_ok for ev in events]
        assert verdicts == [True] * 3 + [False] + [True] * 12
    finally:
        rt.reset_for_tests()


# ------------------------------------------------------- profiler


def test_profiler_folded_stacks_name_threads():
    """The sampler's folded output is flamegraph.pl-loadable
    "thread;frame;frame count" lines, root-first, and names live
    threads by their thread name."""
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(range(200))

    t = threading.Thread(target=spin, name="sat-spin", daemon=True)
    t.start()
    sampler = profiler.StackSampler(hz=200.0)
    sampler.start()
    try:
        time.sleep(0.4)
        text = sampler.folded(10.0)
    finally:
        sampler.stop()
        stop.set()
        t.join(timeout=2.0)

    lines = text.splitlines()
    assert lines, "sampler collected nothing"
    for line in lines:
        stack, count = line.rsplit(" ", 1)
        assert int(count) >= 1
        assert ";" in stack  # thread;frame at minimum
    assert any(ln.startswith("sat-spin;") for ln in lines)


def test_profiler_off_by_default_is_noop():
    """profile_hz=0 (the default) must leave the process untouched: no
    module-global sampler, no babble-profiler thread, and a node built
    from the default config never acquires one."""
    assert profiler.active() is None
    assert not any(t.name == "babble-profiler"
                   for t in threading.enumerate())
    conf = fast_config()
    assert conf.profile_hz == 0.0


def test_profiler_burst_fallback():
    """burst_folded: the /debug/flame path when no sampler is running
    — inline sampling for the request window. The calling thread is
    skipped (it would only ever show the sampler loop), so give it a
    sibling to observe, as a live node always would."""
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(range(200))

    t = threading.Thread(target=spin, name="sat-burst", daemon=True)
    t.start()
    try:
        text = profiler.burst_folded(0.25, hz=100.0)
    finally:
        stop.set()
        t.join(timeout=2.0)
    lines = text.splitlines()
    assert lines, "burst sampling collected nothing"
    assert any(ln.startswith("sat-burst;") for ln in lines)


# ------------------------------------------- live-scrape attribution


def _scrape(svc):
    with urllib.request.urlopen(
            f"http://{svc.addr}/metrics", timeout=10) as r:
        return promtext.parse(r.read().decode())


def test_live_scrape_thread_cpu_and_queue_families():
    """A live 3-node net's /metrics scrape carries every saturation
    family, and the thread CPU counters attribute CPU-seconds to the
    named node threads (gossip loop, worker)."""
    nodes = make_nodes(3, "inmem")
    svc = None
    try:
        svc = Service("127.0.0.1:0", nodes[0])
        svc.serve_async()
        run_gossip(nodes, target_round=3, shutdown=False)
        samples, _ = _scrape(svc)
        missing = promtext.check_series(samples, SATURATION_FAMILIES)
        assert not missing, missing
        threads = {lb.get("thread")
                   for lb, _v in samples["babble_thread_cpu_seconds_total"]}
        assert any(t and t.startswith("babble-gossip") for t in threads), \
            threads
        assert any(t and t.startswith("babble-worker") for t in threads), \
            threads
        total = sum(
            v for _lb, v in samples["babble_thread_cpu_seconds_total"])
        assert total > 0.0
    finally:
        if svc is not None:
            svc.close()
        for nd in nodes:
            nd.shutdown()


# ------------------------------------------------ bottleneck naming


class _SlowProxy(InmemAppProxy):
    """Application that can't keep up: every commit_block stalls the
    node's worker thread, so upstream work backs up in _work."""

    def commit_block(self, block):
        time.sleep(0.3)
        return super().commit_block(block)


def _build_net(n, work_queue=None, commit_queue=None,
               consensus_interval=0.0, proxy_cls=InmemAppProxy,
               profile_hz=0.0):
    transports = [InmemTransport(f"addr{i}", timeout=2.0)
                  for i in range(n)]
    connect_all(transports)
    entries = make_keyed_peers(n, addr_fn=lambda i: f"addr{i}")
    by_addr = {t.local_addr(): t for t in transports}
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = fast_config(heartbeat=0.01)
        if work_queue is not None:
            conf.work_queue = work_queue
        if commit_queue is not None:
            conf.commit_queue = commit_queue
        conf.consensus_interval = consensus_interval
        conf.profile_hz = profile_hz
        store = InmemStore(participants, CACHE)
        node = Node(conf, i, key, peers, store,
                    by_addr[peer.net_addr], proxy_cls())
        node.init()
        nodes.append(node)
    return nodes


def test_saturated_net_names_bottleneck_queue():
    """The acceptance criterion: saturate a 3-node net (an app whose
    commit_block stalls the node worker for 300 ms per block) and the
    bottleneck queue is identifiable BY NAME from a live scrape —
    `work`'s wait p99 exceeds every other queue's on the node (every
    rpc/tx/block item sits behind the stalled worker), and its depth
    rides capacity whenever the worker is inside a block."""
    cap = 8
    nodes = _build_net(3, work_queue=cap, proxy_cls=_SlowProxy)
    svc = None
    try:
        svc = Service("127.0.0.1:0", nodes[0])
        svc.serve_async()
        for nd in nodes:
            nd.run_async(gossip=True)
        deadline = time.monotonic() + 60.0
        i = 0
        samples = None
        max_depth = 0.0
        p99: dict = {}

        def queue_p99s(s):
            out = {}
            for qname in {lb["queue"] for lb, v in
                          s.get("babble_queue_wait_seconds_count", [])
                          if lb.get("node") == "0" and v > 0}:
                snap = promtext.histogram_snapshot(
                    s, "babble_queue_wait_seconds",
                    {"queue": qname, "node": "0"})
                if snap.count:
                    out[qname] = snap.quantile(0.99)
            return out

        while time.monotonic() < deadline:
            nodes[i % 3].submit_tx(f"sat tx {i}".encode())
            i += 1
            if i % 200 == 0:
                samples, _ = _scrape(svc)
                depth = [v for lb, v in samples["babble_queue_depth"]
                         if lb.get("queue") == "work"
                         and lb.get("node") == "0"]
                max_depth = max(max_depth, depth[0] if depth else 0)
                waits = [v for lb, v in
                         samples["babble_queue_wait_seconds_count"]
                         if lb.get("queue") == "work"
                         and lb.get("node") == "0"]
                p99 = queue_p99s(samples)
                # Mature saturation: the slow-block waits own the
                # histogram tail and the backlog has ridden capacity
                # at least once under this scrape's eyes.
                if (max_depth >= cap - 1
                        and waits and waits[0] >= 1000
                        and p99.get("work", 0) > 0.1
                        and all(v < p99["work"]
                                for q, v in p99.items() if q != "work")):
                    break
            time.sleep(0.002)
        assert samples is not None, "never scraped"

        # Depth rode capacity while the worker was stalled.
        assert max_depth >= cap - 1, \
            f"work depth peaked at {max_depth}, capacity {cap}"
        # The bottleneck is `work` BY NAME: wait p99 over 100 ms (the
        # 300 ms block stalls) and above every other queue on the
        # node, from the same scrape a dashboard would read.
        assert p99.get("work", 0) > 0.1, p99
        for qname, v in p99.items():
            if qname != "work":
                assert p99["work"] > v, p99
    finally:
        if svc is not None:
            svc.close()
        for nd in nodes:
            nd.shutdown()


# ------------------------------------------------------ /debug/flame


def test_debug_flame_names_consensus_and_gossip_threads():
    """GET /debug/flame returns non-empty folded stacks naming at
    least the consensus and gossip threads (acceptance criterion) —
    here with the sampler ON via Config.profile_hz, serving from the
    ring rather than the burst fallback."""
    nodes = _build_net(3, consensus_interval=0.05, profile_hz=199.0)
    svc = None
    try:
        svc = Service("127.0.0.1:0", nodes[0])
        svc.serve_async()
        for nd in nodes:
            nd.run_async(gossip=True)
        assert profiler.active() is not None, \
            "profile_hz>0 must acquire the process sampler"
        deadline = time.monotonic() + 20.0
        roots = set()
        i = 0
        while time.monotonic() < deadline:
            nodes[i % 3].submit_tx(f"flame tx {i}".encode())
            i += 1
            if i % 150 == 0:
                with urllib.request.urlopen(
                        f"http://{svc.addr}/debug/flame?seconds=2",
                        timeout=10) as r:
                    text = r.read().decode()
                roots = {ln.split(";", 1)[0]
                         for ln in text.splitlines() if ln.strip()}
                if (any(r0.startswith("babble-consensus") for r0 in roots)
                        and any(r0.startswith("babble-gossip")
                                for r0 in roots)):
                    break
            time.sleep(0.002)
        assert any(r0.startswith("babble-consensus") for r0 in roots), roots
        assert any(r0.startswith("babble-gossip") for r0 in roots), roots
    finally:
        if svc is not None:
            svc.close()
        for nd in nodes:
            nd.shutdown()
        assert profiler.active() is None, \
            "shutdown must release the process sampler"


# --------------------------------------------------- /debug columns


def test_debug_endpoints_carry_queue_columns():
    """/debug/gossip and /debug/peers surface the queue accounting
    (saturation snapshot + per-peer push-window occupancy) from the
    same instruments /metrics exports — no second bookkeeping path."""
    nodes = make_nodes(3, "inmem")
    svc = None
    try:
        svc = Service("127.0.0.1:0", nodes[0])
        svc.serve_async()
        run_gossip(nodes, target_round=2, shutdown=False)
        with urllib.request.urlopen(
                f"http://{svc.addr}/debug/gossip", timeout=10) as r:
            gossip = json.load(r)
        assert "queues" in gossip
        assert {"commit", "work"} <= set(gossip["queues"])
        for snap in gossip["queues"].values():
            assert {"depth", "capacity", "wait_p99_ms",
                    "dropped"} <= set(snap)
        with urllib.request.urlopen(
                f"http://{svc.addr}/debug/peers", timeout=10) as r:
            peers = json.load(r)
        windows = [row.get("push_window")
                   for row in peers["peers"].values()]
        assert any(w is not None for w in windows), peers
        for w in windows:
            if w is not None:
                assert {"depth", "occupancy", "eager"} <= set(w)
    finally:
        if svc is not None:
            svc.close()
        for nd in nodes:
            nd.shutdown()


# ------------------------------------------------- dashboard lint


def test_dashboard_metric_families_exist():
    """Grafana drift lint: every babble_* family a dashboard panel
    references must exist — in a live scrape of a 3-node net, or (for
    config-gated planes: file-store fsync, chaos faults, clock) as a
    family declared somewhere in the source tree. The saturation
    families must be in the LIVE scrape, not just declared."""
    import glob
    import os
    import re

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    dash = json.load(open(
        os.path.join(repo, "docs", "grafana", "babble-tpu.json")))

    def family(name):
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf):
                return name[:-len(suf)]
        return name

    referenced = set()
    for panel in dash["panels"]:
        for tgt in panel.get("targets", []):
            for fam in re.findall(r"babble_[a-z0-9_]+",
                                  tgt.get("expr", "")):
                referenced.add(family(fam))
    assert referenced, "dashboard references no babble_* families"

    declared = set()
    for path in glob.glob(os.path.join(repo, "babble_tpu", "**", "*.py"),
                          recursive=True):
        with open(path) as fh:
            declared.update(re.findall(r'"(babble_[a-z0-9_]+)"',
                                       fh.read()))

    nodes = make_nodes(3, "inmem")
    svc = None
    try:
        svc = Service("127.0.0.1:0", nodes[0])
        svc.serve_async()
        run_gossip(nodes, target_round=2, shutdown=False)
        samples, _ = _scrape(svc)
        live = {family(name) for name in samples}
        missing = referenced - live - declared
        assert not missing, (
            f"dashboard references families that exist nowhere: "
            f"{sorted(missing)}")
        # The new observability plane must be live, not merely
        # declared-but-dead in the source.
        assert not promtext.check_series(samples, SATURATION_FAMILIES)
    finally:
        if svc is not None:
            svc.close()
        for nd in nodes:
            nd.shutdown()


# --------------------------------------------------- multicore soak


def test_multicore_soak_leg_smoke(tmp_path):
    """bench.py's soak leg at n=3 emits the saturation extensions:
    per-family queue summary, bottleneck name, role-folded thread CPU
    seconds, and the saturation/CPU time-series rows."""
    import bench

    ts_file = tmp_path / "soak_ts.jsonl"
    leg = bench.gossip_soak_leg(3, 6.0, 2.0, str(ts_file))
    assert leg["events_per_s"] > 0
    assert leg["queues"], leg
    assert {"commit", "work"} <= set(leg["queues"])
    for row in leg["queues"].values():
        assert {"depth", "capacity", "wait_p99_ms", "dropped"} <= set(row)
    assert leg["bottleneck_queue"] in leg["queues"]
    assert leg["queue_wait_p99_ms"] >= 0.0
    assert leg["thread_cpu_s"], leg
    assert any(k.startswith("babble-") for k in leg["thread_cpu_s"])
    rows = [json.loads(ln) for ln in
            ts_file.read_text().splitlines()]
    assert any(r.get("node") == "sat" for r in rows)
    assert any(r.get("node") == "cpu" for r in rows)
