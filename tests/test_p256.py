"""Device-side P-256 batch verify (ops/p256.py) — parity pins.

The kernel's gate is VERDICT PARITY, not speed: every test compares the
vmapped JAX kernel's verdict list bit-for-bit against the pure-Python
fallback on the same vectors, including the r/s range rejections, the
high-s encoding, the Shamir-trick degeneracies (point at infinity,
u1 == u2 doubling), and the malformed-creator None contract. One
8-lane kernel compile (~20 s on CPU) is shared by the whole module —
keep batches at 8 or below so no second ladder size compiles.
"""

import pytest

jax = pytest.importorskip("jax")

from babble_tpu.crypto import _fallback as fb  # noqa: E402
from babble_tpu.ops import p256  # noqa: E402
from tests.test_crypto import _batch_vectors  # noqa: E402


def test_available():
    assert p256.available()


def test_device_verify_batch_parity():
    """The full mixed corpus — valid / corrupt / high-s / r range /
    malformed creator — verdict-identical to the host fallback."""
    pubs, digests, sigs, expected = _batch_vectors()
    assert fb.verify_batch(pubs, digests, sigs) == expected
    # chunks of <= 8 keep the kernel on the single compiled ladder size
    got = []
    for i in range(0, len(pubs), 8):
        got += p256.verify_batch(
            pubs[i:i + 8], digests[i:i + 8], sigs[i:i + 8])
    assert got == expected


def test_device_degeneracies():
    """d=1 (Q = G) degeneracies: r = (N - z) mod N lands the Shamir
    sum on the point at infinity (reject), r = z mod N forces
    u1 == u2 through the add formula's doubling branch."""
    from babble_tpu import crypto

    k1 = fb.key_from_seed(0)
    assert k1.d == 1
    pub = fb.pub_key_bytes(k1)
    d = crypto.sha256(b"degenerate")
    z = int.from_bytes(d, "big") % fb.N
    sigs = [((fb.N - z) % fb.N or 1, 1), (z or 1, 1)]
    expected = fb.verify_batch([pub, pub], [d, d], sigs)
    assert p256.verify_batch([pub, pub], [d, d], sigs) == expected


def test_device_padding_lanes_ignored():
    """A batch smaller than the 8-lane ladder pads with copies of lane
    0; the pad lanes' verdicts must not leak into the result."""
    key = fb.key_from_seed(77)
    pub = fb.pub_key_bytes(key)
    from babble_tpu import crypto

    d = crypto.sha256(b"lane")
    r, s = fb.sign(key, d)
    assert p256.verify_batch([pub], [d], [(r, s)]) == [True]
    assert p256.verify_batch([pub], [d], [(r, s + 1)]) == [False]


def test_ingest_routes_device_backend(monkeypatch):
    """verify_events(..., device_verify=True) routes through the
    p256 kernel and memoizes the same verdicts the host path would."""
    from babble_tpu.hashgraph.event import Event
    from babble_tpu.node import ingest

    key = fb.key_from_seed(5)
    pub = fb.pub_key_bytes(key)
    events = []
    for i in range(3):
        ev = Event.new([b"tx-%d" % i], ["p0", "p1"], pub, i)
        ev.sign(key)
        ev._sig_ok = None  # drop sign()'s memo: force real verification
        events.append(ev)
    events[1].r = int(events[1].r) + 1  # corrupt position 1

    calls = []
    real = p256.verify_batch

    def spying(pubs, digests, sigs):
        calls.append(len(pubs))
        return real(pubs, digests, sigs)

    monkeypatch.setattr(p256, "verify_batch", spying)
    assert ingest.active_backend(True) == "device-p256"
    ingest.verify_events(events, workers=4, device_verify=True)
    assert calls == [3]
    assert [ev._sig_ok for ev in events] == [True, False, True]
