"""Test environment: force JAX onto CPU with 8 virtual devices so
multi-chip sharding paths compile and execute without TPU hardware.

Env vars alone are not enough here: the environment's sitecustomize
initializes the TPU backend before pytest starts, so we go through
babble_tpu.devices.ensure_virtual_devices, which clears the backend
cache and re-initializes onto the virtual CPU platform."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from babble_tpu.devices import ensure_virtual_devices

ensure_virtual_devices(8)
