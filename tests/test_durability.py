"""Crash-durability unit suite (docs/robustness.md "Crash recovery").

In-process counterpart of tests/test_crash.py: transaction-protocol
semantics on FileStore (batch atomicity, torn-tail discard, anchors,
idempotent close), FileStore.load round-trip parity against an
InmemStore oracle, exactly-once block redelivery across a reload, the
journal proxy's dedupe, and the node's shutdown drain.

Process death is simulated by closing the raw sqlite connection with a
transaction open — sqlite discards an uncommitted transaction on
recovery exactly as it would after SIGKILL (no commit frame in the
WAL)."""

from __future__ import annotations

import json
import os
import sqlite3

import pytest

from babble_tpu.common import StoreError
from babble_tpu.hashgraph import (
    Block,
    FileStore,
    Hashgraph,
    InmemStore,
    RoundInfo,
)
from babble_tpu.hashgraph.event import event_from_json_obj
from babble_tpu.proxy import FileAppProxy

from test_store import make_participants, signed_event


def _chain(keys, pubs, per_creator=6, start_ts=1_700_000_000_000_000_000):
    """A simple two-creator event chain with topo indexes assigned."""
    heads = {p: "" for p in pubs}
    events = []
    ts = start_ts
    topo = 0
    for idx in range(per_creator):
        for k, p in zip(keys, pubs):
            ev = signed_event(k, p, [heads[p], ""], idx, ts)
            ts += 1000
            ev.topological_index = topo
            topo += 1
            heads[p] = ev.hex()
            events.append(ev)
    return events


def _kill(fs: FileStore) -> None:
    """Simulate SIGKILL: drop the connection with whatever transaction
    is open; sqlite rolls the uncommitted tail back on next open."""
    fs._db.close()


# ------------------------------------------------- batch atomicity


def test_batch_commit_is_atomic_across_kill(tmp_path):
    keys, pubs, participants = make_participants(2)
    path = str(tmp_path / "s.db")
    events = _chain(keys, pubs, per_creator=2)

    fs = FileStore(participants, 100, path)
    fs.begin_batch()
    for ev in events[:2]:
        fs.set_event(ev)
    fs.commit_batch()          # first sync batch: durable
    fs.begin_batch()
    for ev in events[2:]:
        fs.set_event(ev)
    _kill(fs)                  # killed mid-second-batch: torn

    fs2 = FileStore.load(100, path)
    for ev in events[:2]:
        assert fs2.has_event(ev.hex()), "committed batch lost"
    for ev in events[2:]:
        assert not fs2.has_event(ev.hex()), "partial sync batch visible"
    fs2.close()


def test_batch_nesting_commits_once_at_outermost(tmp_path):
    keys, pubs, participants = make_participants(2)
    fs = FileStore(participants, 100, str(tmp_path / "n.db"))
    ev0, ev1 = _chain(keys, pubs, per_creator=1)
    fs.begin_batch()
    fs.begin_batch()
    fs.set_event(ev0)
    fs.commit_batch()          # inner: must NOT commit yet
    inner_commits = fs.fsync_count
    fs.set_event(ev1)
    fs.commit_batch()          # outermost: one durable commit
    assert fs.fsync_count == inner_commits + 1
    fs.close()

    fs2 = FileStore.load(100, str(tmp_path / "n.db"))
    assert fs2.has_event(ev0.hex()) and fs2.has_event(ev1.hex())
    fs2.close()


def test_rollback_batch_discards_durable_writes(tmp_path):
    keys, pubs, participants = make_participants(2)
    path = str(tmp_path / "rb.db")
    fs = FileStore(participants, 100, path)
    ev0, ev1 = _chain(keys, pubs, per_creator=1)
    fs.set_event(ev0)
    fs.begin_batch()
    fs.set_event(ev1)
    fs.rollback_batch()
    fs.close()
    fs2 = FileStore.load(100, path)
    assert fs2.has_event(ev0.hex())
    assert not fs2.has_event(ev1.hex())
    fs2.close()


def test_torn_consensus_pass_leaves_no_partial_rounds(tmp_path):
    """Round/block writes of an interrupted pass are invisible after
    reload: the transaction died with the process, and the load-time
    recovery additionally discards anything above the consensus
    anchor."""
    keys, pubs, participants = make_participants(2)
    path = str(tmp_path / "t.db")
    fs = FileStore(participants, 100, path)
    # one COMPLETE pass: round 0 + block 0, committed atomically
    ri = RoundInfo()
    ri.add_event("0xAA", True)
    fs.begin_batch()
    fs.set_round(0, ri)
    fs.set_block(Block(0, [b"tx0"]))
    fs.commit_batch()
    assert fs.consensus_anchor() == 0
    # a second pass interrupted mid-write
    fs.begin_batch()
    ri1 = RoundInfo()
    ri1.add_event("0xBB", True)
    fs.set_round(1, ri1)
    fs.set_block(Block(1, [b"tx1"]))
    _kill(fs)

    fs2 = FileStore.load(100, path)
    assert fs2.consensus_anchor() == 0
    assert fs2.get_round(0).events  # complete pass intact
    assert fs2.get_block(0).transactions == [b"tx0"]
    with pytest.raises(StoreError):
        fs2.get_round(1)
    with pytest.raises(StoreError):
        fs2.get_block(1)
    fs2.close()


def test_load_discards_rounds_above_anchor(tmp_path):
    """Defense for pre-transactional writers: rounds/blocks committed
    per-statement past the anchor (a crafted or legacy tail) are
    discarded at load so bootstrap recomputes them from events."""
    keys, pubs, participants = make_participants(2)
    path = str(tmp_path / "a.db")
    fs = FileStore(participants, 100, path)
    ri = RoundInfo()
    ri.add_event("0xAA", True)
    fs.set_round(0, ri)        # per-statement commit advances anchor to 0
    fs.close()
    # sneak a round + block past the anchor behind FileStore's back
    db = sqlite3.connect(path)
    db.execute("INSERT INTO rounds VALUES (7, ?)",
               (json.dumps({"Events": {}}),))
    db.execute("INSERT INTO blocks VALUES (7, ?)",
               (json.dumps({"RoundReceived": 7, "Transactions": []}),))
    db.commit()
    db.close()

    fs2 = FileStore.load(100, path)
    assert fs2.consensus_anchor() == 0
    with pytest.raises(StoreError):
        fs2.get_round(7)
    with pytest.raises(StoreError):
        fs2.get_block(7)
    fs2.close()


def test_legacy_db_without_meta_migrates(tmp_path):
    """A database written before the meta table existed loads cleanly:
    anchors seeded from its content, schema version stamped."""
    keys, pubs, participants = make_participants(2)
    path = str(tmp_path / "legacy.db")
    fs = FileStore(participants, 100, path)
    ev = _chain(keys, pubs, per_creator=1)[0]
    fs.set_event(ev)
    ri = RoundInfo()
    ri.add_event(ev.hex(), True)
    fs.set_round(0, ri)
    fs.set_block(Block(0, [b"tx"]))
    fs.close()
    db = sqlite3.connect(path)
    db.execute("DROP TABLE meta")
    db.commit()
    db.close()

    fs2 = FileStore.load(100, path)
    assert fs2.schema_version() == 2
    assert fs2.consensus_anchor() == 0
    # legacy semantics preserved: everything present was treated as
    # delivered, so a bootstrap re-emits nothing
    assert fs2.last_committed_block() == 0
    assert fs2.get_round(0).events
    fs2.close()


# ----------------------------------------------------- close / sync


def test_close_is_idempotent_and_exception_safe(tmp_path):
    keys, pubs, participants = make_participants(2)
    path = str(tmp_path / "c.db")
    fs = FileStore(participants, 100, path)
    ev = _chain(keys, pubs, per_creator=1)[0]
    fs.set_event(ev)
    fs.close()
    fs.close()                 # double close: no raise
    fs.close()

    # close with an interrupted batch open: rolled back, no raise
    fs2 = FileStore.load(100, path)
    ev2 = _chain(keys, pubs, per_creator=2)[3]
    fs2.begin_batch()
    fs2.set_event(ev2)
    fs2.close()
    fs2.close()
    fs3 = FileStore.load(100, path)
    assert fs3.has_event(ev.hex())
    assert not fs3.has_event(ev2.hex()), (
        "half-open batch committed by close")
    fs3.close()
    # writes after close never raise out of the durable marker path
    fs3.set_last_committed_block(99)


@pytest.mark.parametrize("sync,level", [("always", 2), ("batch", 1),
                                        ("off", 0)])
def test_store_sync_policy_sets_pragma(tmp_path, sync, level):
    _, _, participants = make_participants(2)
    fs = FileStore(participants, 10, str(tmp_path / f"{sync}.db"),
                   sync=sync)
    assert fs._db.execute("PRAGMA synchronous").fetchone()[0] == level
    assert fs.durability_stats()["store_sync"] == sync
    fs.close()


def test_store_sync_rejects_unknown_policy(tmp_path):
    _, _, participants = make_participants(2)
    with pytest.raises(ValueError):
        FileStore(participants, 10, str(tmp_path / "x.db"), sync="fsync")


def test_durability_stats_counts_commits(tmp_path):
    keys, pubs, participants = make_participants(2)
    fs = FileStore(participants, 100, str(tmp_path / "d.db"))
    before = fs.durability_stats()["fsync_count"]
    for ev in _chain(keys, pubs, per_creator=2):
        fs.set_event(ev)
    d = fs.durability_stats()
    assert d["fsync_count"] == before + 4
    assert d["fsync_total_ns"] > 0
    assert d["last_committed_block"] == -1
    fs.set_last_committed_block(3)
    assert fs.durability_stats()["last_committed_block"] == 3
    fs.close()


# ------------------------------------- load parity vs inmem oracle


def test_file_store_load_parity_with_inmem_oracle(tmp_path):
    """Persist a converged hashgraph, reload + bootstrap, and hold
    every read surface to an InmemStore oracle that ran the identical
    DAG: known, rounds, witnesses, blocks, event-object windows."""
    from fixtures import build_consensus_graph

    h, b = build_consensus_graph()
    participants = b.participants()
    path = str(tmp_path / "parity.db")

    fs = FileStore(participants, 1000, path)
    h_file = Hashgraph(participants, fs)
    oracle_store = InmemStore(participants, 1000)
    h_oracle = Hashgraph(participants, oracle_store)
    for ev in b.ordered_events:
        for target in (h_file, h_oracle):
            target.insert_event(
                event_from_json_obj(json.loads(ev.marshal())), True)
    h_file.run_consensus()
    h_oracle.run_consensus()
    fs.close()

    fs2 = FileStore.load(1000, path)
    h2 = Hashgraph(participants, fs2)
    h2.bootstrap()

    assert fs2.known() == oracle_store.known()
    assert h2.consensus_events() == h_oracle.consensus_events()
    assert h2.last_consensus_round == h_oracle.last_consensus_round
    assert fs2.last_round() == oracle_store.last_round()
    for r in range(oracle_store.last_round() + 1):
        want = oracle_store.get_round(r)
        got = fs2.get_round(r)
        assert sorted(got.witnesses()) == sorted(want.witnesses()), r
        assert {x: (e.witness, e.famous) for x, e in got.events.items()} \
            == {x: (e.witness, e.famous) for x, e in want.events.items()}, r
        want_block = None
        try:
            want_block = oracle_store.get_block(r)
        except StoreError:
            pass
        if want_block is not None:
            assert fs2.get_block(r).marshal() == want_block.marshal(), r
    for pk in participants:
        want_objs = oracle_store.participant_event_objects(pk, -1)
        got_objs = fs2.participant_event_objects(pk, -1)
        assert [e.hex() for e in got_objs] == [e.hex() for e in want_objs]
        assert [e.topological_index for e in got_objs] \
            == [e.topological_index for e in want_objs]
        assert fs2.last_from(pk) == oracle_store.last_from(pk)
    fs2.close()


# -------------------------------------------- exactly-once redelivery


def test_bootstrap_redelivers_only_above_durable_anchor(tmp_path):
    """Blocks at or below last_committed_block were delivered before
    the crash and must NOT re-emit; blocks above it (decided, never
    durably delivered) must re-emit byte-identically."""
    from fixtures import build_consensus_graph

    h, b = build_consensus_graph()
    participants = b.participants()
    path = str(tmp_path / "eo.db")

    committed = []
    fs = FileStore(participants, 1000, path)
    h1 = Hashgraph(participants, fs, commit_callback=committed.append)
    for ev in b.ordered_events:
        h1.insert_event(
            event_from_json_obj(json.loads(ev.marshal())), True)
    h1.run_consensus()
    assert committed, "fixture must commit a block"
    # the crash beat every delivery to the durable marker: the anchor
    # is still -1, so the reload must re-emit the whole committed tail
    # byte-identically
    fs.close()

    redelivered = []
    fs2 = FileStore.load(1000, path)
    h2 = Hashgraph(participants, fs2, commit_callback=redelivered.append)
    h2.bootstrap()
    assert [blk.marshal() for blk in redelivered] \
        == [blk.marshal() for blk in committed]
    fs2.close()

    # fully-delivered store: a reload re-emits nothing
    fs3 = FileStore.load(1000, path)
    fs3.set_last_committed_block(committed[-1].round_received)
    silent = []
    h3 = Hashgraph(participants, fs3, commit_callback=silent.append)
    h3.bootstrap()
    assert silent == []
    assert h3.consensus_events() == h1.consensus_events()
    fs3.close()


# ------------------------------------------------- journal app proxy


def test_file_app_proxy_journal_and_restart_dedupe(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    p1 = FileAppProxy(path)
    p1.commit_block(Block(3, [b"a", b"b"]))
    p1.commit_block(Block(5, [b"c"]))
    assert p1.last_round() == 5
    assert p1.committed_transactions() == [b"a", b"b", b"c"]
    p1.close()

    # restart: redelivery at/below the journal tail is dropped,
    # new blocks append
    p2 = FileAppProxy(path)
    assert p2.last_round() == 5
    p2.commit_block(Block(5, [b"c"]))      # crash-window redelivery
    p2.commit_block(Block(4, [b"stale"]))  # below tail
    p2.commit_block(Block(7, [b"d"]))
    assert p2.committed_transactions() == [b"a", b"b", b"c", b"d"]
    p2.close()

    with open(path) as fh:
        rounds = [json.loads(line)["round"] for line in fh]
    assert rounds == [3, 5, 7]


def test_file_app_proxy_ignores_torn_final_line(tmp_path):
    path = str(tmp_path / "torn.jsonl")
    p1 = FileAppProxy(path)
    p1.commit_block(Block(2, [b"a"]))
    p1.close()
    with open(path, "a") as fh:
        fh.write('{"round": 9, "txs": ["ff')  # killed mid-write
    p2 = FileAppProxy(path)
    assert p2.last_round() == 2
    p2.commit_block(Block(3, [b"b"]))  # continues past the torn line
    assert p2.committed_transactions() == [b"a", b"b"]
    p2.close()


# --------------------------------------------------- shutdown drain


def test_shutdown_drains_queued_blocks(tmp_path):
    """Blocks the consensus worker decided but the background worker
    never delivered are delivered (and durably marked) by shutdown
    instead of dropped on the floor."""
    from babble_tpu.net import InmemTransport
    from babble_tpu.node import Node
    from babble_tpu.node.config import test_config
    from babble_tpu.proxy import InmemAppProxy

    from test_node import make_keyed_peers

    entries = make_keyed_peers(1)
    key, peer = entries[0]
    participants = {peer.pub_key_hex: 0}
    store = InmemStore(participants, 1000)
    proxy = InmemAppProxy()
    node = Node(test_config(), 0, key, [peer],
                store, InmemTransport(peer.net_addr), proxy)
    node.init()
    node.commit_ch.put(Block(1, [b"queued"]))
    node.shutdown()
    assert proxy.committed_transactions() == [b"queued"]
    assert store.last_committed_block() == 1


def test_shutdown_drains_in_delivery_order(tmp_path):
    """The commit_ch forwarder moves blocks commit_ch -> _work, so at
    shutdown _work holds the OLDER undelivered blocks. Draining
    commit_ch first would advance the durable anchor and a journaling
    proxy's dedupe line past them, silently dropping their
    transactions — the drain must deliver _work first."""
    from babble_tpu.net import InmemTransport
    from babble_tpu.node import Node
    from babble_tpu.node.config import test_config

    from test_node import make_keyed_peers

    entries = make_keyed_peers(1)
    key, peer = entries[0]
    participants = {peer.pub_key_hex: 0}
    store = InmemStore(participants, 1000)
    proxy = FileAppProxy(str(tmp_path / "drain.jsonl"))
    node = Node(test_config(), 0, key, [peer],
                store, InmemTransport(peer.net_addr), proxy)
    node.init()
    node._work.put(("block", Block(1, [b"older"])))  # forwarded earlier
    node.commit_ch.put(Block(2, [b"newer"]))         # still in commit_ch
    node.shutdown()
    assert proxy.committed_transactions() == [b"older", b"newer"]
    assert store.last_committed_block() == 2
    proxy.close()


def test_node_bootstrap_replay_does_not_route_through_commit_ch(tmp_path):
    """commit_ch is bounded (400) and its consumer only starts in
    run(): a torn-tail replay longer than the bound would deadlock
    init if re-emitted blocks were put on the queue. The node must
    buffer the replay and deliver it synchronously during init."""
    from babble_tpu.net import InmemTransport
    from babble_tpu.net.peer import Peer
    from babble_tpu.node import Node
    from babble_tpu.node.config import test_config
    from babble_tpu.proxy import InmemAppProxy

    from fixtures import CONSENSUS_PLAYS, GraphBuilder

    path = str(tmp_path / "replay.db")
    committed = []
    # Converge a DAG into a FileStore whose durable anchor never
    # advanced: the whole committed tail is undelivered at "crash".
    b = GraphBuilder(3)
    for i in range(3):
        b.add_initial(f"e{i}", i)
    for p in CONSENSUS_PLAYS:
        b.play(p)
    participants = b.participants()
    fs = FileStore(participants, 1000, path)
    h1 = Hashgraph(participants, fs, commit_callback=committed.append)
    for ev in b.ordered_events:
        h1.insert_event(ev, True)
    h1.run_consensus()
    assert committed, "fixture must leave an undelivered block tail"
    fs.close()

    fs2 = FileStore.load(1000, path)
    peers = [Peer(net_addr=f"addr{n.id}", pub_key_hex=n.pub_hex)
             for n in b.nodes]
    proxy = InmemAppProxy()
    node = Node(test_config(), 0, b.nodes[0].key, peers, fs2,
                InmemTransport("addr0"), proxy)

    def no_queue_put(block):
        raise AssertionError(
            "bootstrap replay must not route through commit_ch")

    node.core.hg.commit_callback = no_queue_put
    node.init(bootstrap=True)
    want = [tx for blk in committed for tx in (blk.transactions or [])]
    assert proxy.committed_transactions() == want
    assert fs2.last_committed_block() == committed[-1].round_received
    fs2.close()
