"""Peer health circuit breaker (HealthTrackingPeerSelector) and the
bounded gossip-pull retry.

Unit tests drive the breaker state machine with a fake clock and a
seeded rng (fully deterministic); the integration test proves the
production property: a dead peer is suspended instead of burning a
gossip slot on every unlucky pick, and is probed and reinstated when it
comes back."""

from __future__ import annotations

import random
import time

from babble_tpu.net import TransportError
from babble_tpu.net.peer import Peer
from babble_tpu.node import HealthTrackingPeerSelector
from babble_tpu.node.peer_selector import CLOSED, HALF_OPEN, OPEN

from test_node import check_gossip, make_nodes


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def make_selector(n=4, **kw):
    peers = [Peer(f"addr{i}", f"0xPUB{i}") for i in range(n)]
    clock = FakeClock()
    kw.setdefault("threshold", 2)
    kw.setdefault("base_backoff", 1.0)
    kw.setdefault("max_backoff", 8.0)
    kw.setdefault("jitter", 0.0)
    kw.setdefault("rng", random.Random(42))
    sel = HealthTrackingPeerSelector(peers, "addr0", clock=clock, **kw)
    return sel, clock


# ------------------------------------------------------------- unit


def test_selector_excludes_self_and_last():
    sel, _ = make_selector(4)
    assert {p.net_addr for p in sel.peers()} == {"addr1", "addr2", "addr3"}
    sel.update_last("addr1")
    picks = {sel.next().net_addr for _ in range(50)}
    assert picks == {"addr2", "addr3"}


def test_breaker_trips_after_threshold_and_backs_off():
    sel, clock = make_selector(4)
    assert not sel.record_failure("addr1")  # 1 of 2: still closed
    assert sel.snapshot()["addr1"]["state"] == CLOSED
    assert sel.record_failure("addr1")  # 2 of 2: tripped
    snap = sel.snapshot()["addr1"]
    assert snap["state"] == OPEN
    assert snap["trips"] == 1
    assert snap["backoff"] == 1.0  # base, jitter 0
    # Suspended: never selected while the deadline is in the future.
    picks = {sel.next().net_addr for _ in range(50)}
    assert "addr1" not in picks


def test_breaker_half_open_probe_then_reinstate():
    sel, clock = make_selector(4)
    sel.record_failure("addr1")
    sel.record_failure("addr1")
    clock.advance(1.01)  # past the (unjittered) 1.0s backoff
    probe = sel.next()
    assert probe.net_addr == "addr1"  # probe preempts healthy picks
    assert sel.snapshot()["addr1"]["state"] == HALF_OPEN
    # While the probe is out (within its window) the peer is not
    # selected again.
    picks = {sel.next().net_addr for _ in range(50)}
    assert "addr1" not in picks
    # Probe succeeded: fully reinstated.
    assert sel.record_success("addr1")  # True = reinstated
    snap = sel.snapshot()["addr1"]
    assert snap["state"] == CLOSED and snap["backoff"] == 0.0
    picks = {sel.next().net_addr for _ in range(100)}
    assert "addr1" in picks


def test_breaker_failed_probe_doubles_backoff_capped():
    sel, clock = make_selector(4)
    sel.record_failure("addr1")
    sel.record_failure("addr1")
    backoffs = [sel.snapshot()["addr1"]["backoff"]]
    for _ in range(5):
        clock.advance(100.0)
        assert sel.next().net_addr == "addr1"  # probe
        assert sel.record_failure("addr1")  # failed probe -> reopen
        backoffs.append(sel.snapshot()["addr1"]["backoff"])
    assert backoffs == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]  # doubles, caps


def test_breaker_jitter_bounds():
    sel, clock = make_selector(4, jitter=0.2)
    sel.record_failure("addr1")
    sel.record_failure("addr1")
    retry_in = sel.snapshot()["addr1"]["retry_in"]
    assert 0.8 <= retry_in <= 1.2  # base 1.0 +/- 20%


def test_all_peers_suspended_returns_none():
    sel, clock = make_selector(3)  # peers addr1, addr2
    for addr in ("addr1", "addr2"):
        sel.record_failure(addr)
        sel.record_failure(addr)
    assert sel.next() is None
    # After the backoff both become probe-able again.
    clock.advance(2.0)
    assert sel.next() is not None


def test_lost_probe_outcome_rearms():
    """A half-open probe whose outcome is never recorded (gossip thread
    died first) must not wedge the peer in HALF_OPEN forever."""
    sel, clock = make_selector(4)
    sel.record_failure("addr1")
    sel.record_failure("addr1")
    clock.advance(1.01)
    assert sel.next().net_addr == "addr1"  # probe dispatched, outcome lost
    clock.advance(10.0)  # probe window long gone
    assert sel.next().net_addr == "addr1"  # re-probed


# ------------------------------------------------------ pull retry


def test_pull_retries_transient_transport_failures():
    nodes = make_nodes(2, "inmem")
    try:
        nodes[1].run_async(gossip=False)  # serve RPCs only
        orig_sync = nodes[0].trans.sync
        calls = {"n": 0}

        def flaky(target, args):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise TransportError("injected transient failure")
            return orig_sync(target, args)

        nodes[0].trans.sync = flaky
        nodes[0].conf.sync_retries = 2
        nodes[0].conf.sync_retry_backoff = 0.01
        sync_limit, known = nodes[0]._pull(nodes[1].local_addr)
        assert not sync_limit and known is not None
        assert calls["n"] == 3
        # Every attempt was a real request; the failures are counted.
        with nodes[0]._stats_lock:
            assert nodes[0].sync_requests == 3
            assert nodes[0].sync_errors == 2
    finally:
        for node in nodes:
            node.shutdown()


def test_pull_retry_bounded():
    nodes = make_nodes(2, "inmem")
    try:
        calls = {"n": 0}

        def always_down(target, args):
            calls["n"] += 1
            raise TransportError("injected dead peer")

        nodes[0].trans.sync = always_down
        nodes[0].conf.sync_retries = 2
        nodes[0].conf.sync_retry_backoff = 0.01
        try:
            nodes[0]._pull(nodes[1].local_addr)
            raise AssertionError("pull should have failed")
        except TransportError:
            pass
        assert calls["n"] == 3  # 1 + sync_retries, no more
    finally:
        for node in nodes:
            node.shutdown()


# ---------------------------------------------------- integration


def test_dead_peer_suspended_then_reinstated():
    """4-node net, one peer dead (unreachable): the running nodes trip
    its breaker and keep gossiping at full speed among themselves;
    when the peer comes back it is probed and reinstated, and the
    whole net converges to one order."""
    nodes = make_nodes(4, "inmem")
    running, dead = nodes[:3], nodes[3]
    dead_addr = dead.local_addr
    # Tight breaker for test speed.
    for nd in running:
        nd.peer_selector = HealthTrackingPeerSelector(
            nd.peer_selector.peers(), nd.local_addr,
            threshold=2, base_backoff=0.3, max_backoff=1.5, jitter=0.1)
        nd.conf.sync_retries = 0  # fail fast: breaker under test
    # Dead = unreachable: instant connect failure, like a dropped box.
    for nd in running:
        nd.trans.disconnect(dead_addr)

    try:
        for nd in running:
            nd.run_async(gossip=True)
        deadline = time.monotonic() + 60.0
        i = 0
        suspended_seen = False
        while time.monotonic() < deadline:
            running[i % 3].submit_tx(f"tx {i}".encode())
            i += 1
            if not suspended_seen:
                suspended_seen = any(
                    nd.get_peer_stats().get(dead_addr, {}).get("trips", 0) > 0
                    for nd in running)
            rounds_ok = all(
                (nd.core.get_last_consensus_round_index() or 0) >= 5
                for nd in running)
            if suspended_seen and rounds_ok:
                break
            time.sleep(0.02)
        else:
            raise AssertionError(
                f"suspended_seen={suspended_seen}, rounds="
                f"{[nd.core.get_last_consensus_round_index() for nd in running]}")

        # The dead peer is suspended, not re-timed-out every round:
        # after the breaker trips, failure counts stop climbing with
        # gossip volume (only sparse probes touch it).
        fails_a = [nd.get_peer_stats()[dead_addr]["failures"]
                   for nd in running]
        time.sleep(1.0)  # plenty of heartbeats at 10ms
        fails_b = [nd.get_peer_stats()[dead_addr]["failures"]
                   for nd in running]
        assert sum(fails_b) - sum(fails_a) <= 9, (
            f"dead peer still hammered: {fails_a} -> {fails_b}")

        # Resurrection: reconnect and run the node.
        for nd in running:
            nd.trans.connect(dead_addr, dead.trans)
        dead.run_async(gossip=True)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            nodes[i % 4].submit_tx(f"tx {i}".encode())
            i += 1
            reinstated = any(
                nd.get_peer_stats()[dead_addr]["state"] == "closed"
                and nd.get_peer_stats()[dead_addr]["successes"] > 0
                for nd in running)
            caught_up = (dead.core.get_last_consensus_round_index() or 0) >= 5
            if reinstated and caught_up:
                break
            time.sleep(0.02)
        else:
            raise AssertionError(
                f"never reinstated: {[nd.get_peer_stats()[dead_addr] for nd in running]}, "
                f"dead round={dead.core.get_last_consensus_round_index()}")
    finally:
        for nd in nodes:
            nd.shutdown()
    check_gossip(nodes)
