"""Parity oracle: the batched TPU engine must reproduce the incremental
host engine (itself asserted against the reference fixtures in
test_hashgraph.py) bit-for-bit on rounds, witness sets, fame trileans,
round-received, consensus timestamps, consensus order, and blocks."""

from __future__ import annotations

import numpy as np
import pytest

from babble_tpu.hashgraph.round_info import Trilean
from babble_tpu.ops import run_consensus_batch
from babble_tpu.ops.kernels import INT32_MAX, ZERO_TS_RANK

from fixtures import (
    build_basic_graph,
    build_consensus_graph,
    build_funky_graph,
    build_round_graph,
)


def host_consensus(h):
    h.divide_rounds()
    h.decide_fame()
    h.find_order()
    return h


def run_both(build):
    h, b = build()
    host_consensus(h)
    res = run_consensus_batch(b.ordered_events, b.participants())
    return h, b, res


@pytest.mark.parametrize(
    "build",
    [build_round_graph, build_consensus_graph, build_funky_graph],
    ids=["round", "consensus", "funky"],
)
def test_rounds_and_witnesses_parity(build):
    h, b, res = run_both(build)
    for eid, ev in enumerate(res.dag.events):
        assert int(res.rounds[eid]) == h.round(ev.hex()), (
            f"round mismatch for {b.get_name(ev.hex())}"
        )
        assert bool(res.witness[eid]) == h.witness(ev.hex()), (
            f"witness mismatch for {b.get_name(ev.hex())}"
        )
    for r in range(h.store.last_round() + 1):
        host_w = set(h.store.round_witnesses(r))
        dev_w = set(res.witnesses_of_round(r))
        assert dev_w == host_w, f"witness set mismatch in round {r}"


@pytest.mark.parametrize(
    "build",
    [build_round_graph, build_consensus_graph, build_funky_graph],
    ids=["round", "consensus", "funky"],
)
def test_fame_parity(build):
    h, b, res = run_both(build)
    for r in range(h.store.last_round() + 1):
        info = h.store.get_round(r)
        for whex in info.witnesses():
            host_fame = info.events[whex].famous
            dev_fame = res.fame_of(whex)
            assert dev_fame == host_fame, (
                f"fame mismatch for {b.get_name(whex)} in round {r}: "
                f"host={host_fame} dev={dev_fame}"
            )
    host_undecided = sorted(set(h.undecided_rounds))
    assert res.undecided_rounds == host_undecided
    assert res.last_consensus_round == h.last_consensus_round


@pytest.mark.parametrize(
    "build",
    [build_round_graph, build_consensus_graph, build_funky_graph],
    ids=["round", "consensus", "funky"],
)
def test_order_and_blocks_parity(build):
    h, b, res = run_both(build)
    # round received + consensus timestamps per event
    for eid, ev in enumerate(res.dag.events):
        host_ev = h.store.get_event(ev.hex())
        host_rr = host_ev.round_received if host_ev.round_received is not None else -1
        assert int(res.round_received[eid]) == host_rr, (
            f"round_received mismatch for {b.get_name(ev.hex())}"
        )
        if host_rr >= 0:
            assert res.consensus_timestamp(eid).ns == host_ev.consensus_timestamp.ns, (
                f"consensus ts mismatch for {b.get_name(ev.hex())}"
            )
    # total order
    assert res.consensus_order == h.consensus_events(), "consensus order mismatch"
    # blocks
    host_blocks = []
    rr_seen = []
    for ehex in h.consensus_events():
        ev = h.store.get_event(ehex)
        if ev.round_received not in rr_seen:
            rr_seen.append(ev.round_received)
            host_blocks.append(h.store.get_block(ev.round_received))
    assert len(res.blocks) == len(host_blocks)
    for dev_b, host_b in zip(res.blocks, host_blocks):
        assert dev_b.round_received == host_b.round_received
        assert dev_b.transactions == host_b.transactions
        assert dev_b.hash() == host_b.hash(), "block hash mismatch"


def test_coordinates_parity_basic():
    """The ancestry fixture exercises coordinates without the full
    insert pipeline (reference hashgraph_test.go:66-133)."""
    h, b = build_basic_graph()
    from babble_tpu.ops import build_dag
    from babble_tpu.ops import kernels

    dag = build_dag(b.ordered_events, b.participants())
    la = np.asarray(
        kernels.compute_last_ancestors(
            dag.self_parent, dag.other_parent, dag.creator, dag.index, dag.levels,
            n=dag.n,
        )
    )
    fd = np.asarray(
        kernels.compute_first_descendants(
            la, dag.creator, dag.index, dag.chain, dag.chain_len, n=dag.n
        )
    )
    for eid, ev in enumerate(dag.events):
        host_ev = h.store.get_event(ev.hex())
        assert la[eid].tolist() == [c.index for c in host_ev.last_ancestors], (
            f"last_anc mismatch for {b.get_name(ev.hex())}"
        )
        assert fd[eid].tolist() == [c.index for c in host_ev.first_descendants], (
            f"first_desc mismatch for {b.get_name(ev.hex())}"
        )


def test_funky_reference_asserts():
    """Re-assert the reference's funky-fixture expectations directly
    against the batched engine (hashgraph_test.go:1539-1588)."""
    h, b = build_funky_graph()
    res = run_consensus_batch(b.ordered_events, b.participants())
    assert int(res.rounds.max()) == 5
    assert res.undecided_rounds == [4, 5]
    # exact per-block tx counts from the reference test
    expected_tx_counts = {1: 6, 2: 7, 3: 7}
    by_rr = {blk.round_received: blk for blk in res.blocks}
    for rr, n_txs in expected_tx_counts.items():
        assert len(by_rr[rr].transactions or []) == n_txs, f"block {rr}"
