"""Engine failover: a wedged device engine is replaced by a host
engine rebuilt from the Store, with no committed block lost or
double-applied and no ordering divergence.

Core-level tests drive a REAL device engine (small-capacity
TpuHashgraph) and compare against the host oracle; the node-level test
injects dispatch failures and watches the watchdog flip the node over
mid-gossip while the net keeps converging."""

from __future__ import annotations

import random
import time

from babble_tpu import crypto
from babble_tpu.hashgraph.inmem_store import InmemStore
from babble_tpu.node import Core
from babble_tpu.node.state import NodeState

from test_node import check_gossip, make_nodes

SMALL_ENGINE = {"capacity": 64, "block": 64, "k_capacity": 8}


def make_cores(n, device_idx=0, commit_log=None):
    keys = [crypto.key_from_seed(9000 + i) for i in range(n)]
    pubs = ["0x" + crypto.pub_key_bytes(k).hex().upper() for k in keys]
    order = sorted(range(n), key=lambda i: pubs[i])
    keys = [keys[i] for i in order]
    pubs = [pubs[i] for i in order]
    participants = {pk: i for i, pk in enumerate(pubs)}
    cores = []
    for i in range(n):
        is_dev = i == device_idx
        cores.append(Core(
            i, keys[i], participants,
            InmemStore(participants, 100000),
            commit_callback=(commit_log.append if is_dev and commit_log
                             is not None else None),
            engine="tpu" if is_dev else "host",
            engine_opts=SMALL_ENGINE if is_dev else None,
        ))
    for c in cores:
        c.init()
    return cores


def gossip_script(cores, steps, seed, consensus_every=5, offset=0):
    rng = random.Random(seed)
    for step in range(steps):
        a, b = rng.sample(range(len(cores)), 2)
        known = cores[a].known()
        diff = cores[b].diff(known)
        if rng.random() < 0.5:
            cores[a].add_transactions(
                [f"tx {offset + step}".encode()])
        cores[a].sync(cores[b].to_wire(diff))
        if step % consensus_every == 0:
            cores[a].run_consensus()
    for c in cores:
        c.run_consensus()


def test_core_failover_preserves_order_and_commits():
    commits = []
    cores = make_cores(4, device_idx=0, commit_log=commits)
    dev = cores[0]
    assert dev.engine_state == "device"

    gossip_script(cores, 160, seed=13)
    assert (dev.get_last_consensus_round_index() or 0) >= 1
    pre_events = list(dev.get_consensus_events())
    pre_commit_rounds = [b.round_received for b in commits]
    pre_head, pre_seq = dev.head, dev.seq
    assert pre_events, "device engine decided nothing pre-failover"

    dev.failover_to_host()

    assert dev.engine_state == "failed_over"
    assert dev.engine_failovers == 1
    assert not dev.supports_pipeline()  # host engine now
    # Identity preserved: the replay recovered the same head/seq.
    assert (dev.head, dev.seq) == (pre_head, pre_seq)
    # Byte-identical order: the host rebuild reproduces the device's
    # committed prefix exactly (it may extend it — the replay runs a
    # full pass over everything the device had not yet folded).
    post_events = dev.get_consensus_events()
    assert post_events[:len(pre_events)] == pre_events
    # No block re-emitted for a round the device already committed.
    post_commit_rounds = [b.round_received for b in commits]
    assert post_commit_rounds[:len(pre_commit_rounds)] == pre_commit_rounds
    new_rounds = post_commit_rounds[len(pre_commit_rounds):]
    assert all(r > max(pre_commit_rounds, default=-1) for r in new_rounds)
    assert len(post_commit_rounds) == len(set(post_commit_rounds))

    # The failed-over core keeps babbling: more gossip, more consensus,
    # still prefix-identical with its host peers.
    gossip_script(cores, 160, seed=14, offset=1000)
    assert len(dev.get_consensus_events()) > len(post_events)
    ref = cores[1].get_consensus_events()
    mine = dev.get_consensus_events()
    m = min(len(ref), len(mine))
    assert m > 0 and ref[:m] == mine[:m]
    # And commits kept flowing post-failover.
    assert len(commits) > len(post_commit_rounds) or len(new_rounds) > 0


def test_core_failover_idempotent_on_host():
    cores = make_cores(2, device_idx=0)
    host = cores[1]
    assert host.engine_state == "host"
    host.failover_to_host()  # no-op on a host core
    assert host.engine_state == "host"
    assert host.engine_failovers == 0


def test_node_watchdog_fails_over_and_net_converges():
    """Force the device pass to raise N times mid-run: the watchdog
    flips the node to the host engine, get_stats() reflects it, no
    committed block is lost, and the net stays byte-identical."""
    nodes = make_nodes(4, "inmem")
    victim = nodes[0]
    for nd in nodes:
        nd.conf.consensus_interval = 0.02  # consensus on the worker
    victim.conf.engine_failover_threshold = 2

    # A fake device seam on the host hashgraph: supports_pipeline()
    # turns true and every dispatch raises — the failure mode of a
    # wedged chip, without needing a real device engine in this test.
    def bad_dispatch(unlocked=None):
        raise RuntimeError("injected device failure")

    victim.core.hg.dispatch_consensus = bad_dispatch
    victim.core.engine_state = "device"
    assert victim.core.supports_pipeline()

    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        deadline = time.monotonic() + 60.0
        i = 0
        while time.monotonic() < deadline:
            nodes[i % 4].submit_tx(f"tx {i}".encode())
            i += 1
            flipped = victim.core.engine_state == "failed_over"
            done = all((nd.core.get_last_consensus_round_index() or 0) >= 5
                       for nd in nodes)
            if flipped and done:
                break
            time.sleep(0.02)
        else:
            raise AssertionError(
                f"engine_state={victim.core.engine_state}, rounds="
                f"{[nd.core.get_last_consensus_round_index() for nd in nodes]}")

        stats = victim.get_stats()
        assert stats["engine_state"] == "failed_over"
        assert int(stats["engine_failovers"]) == 1
        assert victim.state.get_state() == NodeState.BABBLING
    finally:
        for nd in nodes:
            nd.shutdown()
    # Byte-identical order across the failed-over node and its peers.
    check_gossip(nodes)
    # Committed blocks reached the app on the failed-over node too.
    assert len(victim.proxy.committed_transactions()) > 0
