"""Cluster-wide perf attribution (docs/observability.md): the
shared-epoch clock handshake, end-to-end transaction tracing (trace
ids on wire events + Chrome flow events), the tracemerge tool, the
/debug/trace since/epoch modes, and the bench_compare regression
gate's comparison semantics."""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from babble_tpu.gojson import Timestamp
from babble_tpu.hashgraph import InmemStore
from babble_tpu.hashgraph.event import Event, WireBody, WireEvent
from babble_tpu.net import FaultyTransport, InmemTransport
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.node import Node
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.proxy import InmemAppProxy
from babble_tpu.service import Service
from babble_tpu.telemetry import ClusterClock, SpanRing, tracemerge

from test_node import check_gossip, make_keyed_peers

CACHE = 10000


def make_traced_nodes(n, heartbeat=0.01, trace_sample=0.0,
                      skews_ns=None, faults=None, seed=11):
    """An n-node inmem net with per-node trace sampling, injected
    clock skew, and (optionally) a chaos transport fabric."""
    inner = [InmemTransport(f"addr{i}", timeout=2.0) for i in range(n)]
    connect_all(inner)
    if faults:
        wrapped = {t.local_addr(): FaultyTransport(t, seed=seed, **faults)
                   for t in inner}
    else:
        wrapped = {t.local_addr(): t for t in inner}
    entries = make_keyed_peers(n, addr_fn=lambda i: f"addr{i}")
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = fast_config(heartbeat=heartbeat)
        conf.trace_sample = trace_sample
        if skews_ns:
            conf.clock_skew_ns = skews_ns[i]
        store = InmemStore(participants, CACHE)
        node = Node(conf, i, key, peers, store,
                    wrapped[peer.net_addr], InmemAppProxy())
        node.init()
        nodes.append(node)
    return nodes


def bombard(nodes, seconds, until=None, prefix="traced"):
    deadline = time.monotonic() + seconds
    i = 0
    while time.monotonic() < deadline:
        nodes[i % len(nodes)].submit_tx(f"{prefix} tx {i}".encode())
        i += 1
        if until is not None and until():
            return True
        time.sleep(0.02)
    return until() if until is not None else True


# ------------------------------------------------------ cluster clock


def test_cluster_clock_ntp_math():
    clock = ClusterClock()
    # Peer clock runs 1s ahead; symmetric 10ms legs.
    t0 = 1_000_000_000
    one_way = 10_000_000
    peer_ahead = 1_000_000_000
    t1 = t0 + one_way + peer_ahead
    t2 = t1 + 2_000_000  # 2ms processing
    t3 = t0 + 2 * one_way + 2_000_000
    clock.observe("p", t0, t1, t2, t3)
    assert clock.offset_ns("p") == pytest.approx(peer_ahead, abs=1000)
    # min-RTT filter: a later, slower, heavily-asymmetric sample must
    # NOT displace the tight one.
    clock.observe("p", t0, t1 + 500_000_000, t2 + 500_000_000,
                  t3 + 900_000_000)
    assert clock.offset_ns("p") == pytest.approx(peer_ahead, abs=1000)
    # Negative-rtt garbage is dropped.
    clock.observe("q", 100, 50, 60, 90)
    assert clock.offset_ns("q") is None
    # Cluster adjustment: mean of peer offsets with self at 0.
    assert clock.cluster_adjust_ns() == pytest.approx(
        peer_ahead / 2, rel=0.01)
    d = clock.describe()
    assert set(d) == {"wall_offset_ns", "cluster_adjust_ns",
                      "peer_offsets_ns"}


def test_clock_skew_recovered_under_jittered_delay():
    """The acceptance check for the offset handshake: two nodes whose
    clocks disagree by an injected 250ms, gossiping over a chaos
    transport with 0-50ms jittered delay, converge to an offset
    estimate within tolerance of the injected skew (the min-RTT filter
    eats the jitter)."""
    skew = 250_000_000  # node 1 runs 250ms ahead
    nodes = make_traced_nodes(
        2, skews_ns=[0, skew],
        faults=dict(delay_min=0.0, delay_max=0.05))
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        addr0, addr1 = nodes[0].local_addr, nodes[1].local_addr

        def converged():
            return (nodes[0].clock.offset_ns(addr1) is not None
                    and nodes[1].clock.offset_ns(addr0) is not None)

        assert bombard(nodes, 20.0, until=converged), \
            "no handshake samples"
        # Let the min-RTT filter see a few more samples.
        bombard(nodes, 2.0)
        tol = 25_000_000  # 25ms on 0-50ms injected jitter
        assert nodes[0].clock.offset_ns(addr1) == pytest.approx(
            skew, abs=tol)
        assert nodes[1].clock.offset_ns(addr0) == pytest.approx(
            -skew, abs=tol)
        # The two nodes' cluster adjustments cancel the skew: their
        # adjusted epochs agree within tolerance.
        e0 = nodes[0].clock.cluster_epoch_ns(0)
        e1 = nodes[1].clock.cluster_epoch_ns(0)
        assert abs(e0 - e1) < tol
    finally:
        for nd in nodes:
            nd.shutdown()


# ------------------------------------------------- trace-id wire form


def _wire_event(trace_id=0):
    body = WireBody(
        transactions=[b"tx"], self_parent_index=3,
        other_parent_creator_id=1, other_parent_index=2, creator_id=0,
        timestamp=Timestamp(1_700_000_000_000_000_000), index=4)
    return WireEvent(body, r=7, s=9, trace_id=trace_id)


def _relay_json(d):
    """JSON-relay a wire dict exactly as the TCP transport does
    (bytes -> std base64 strings)."""
    import base64

    return json.dumps(
        d, default=lambda b: base64.b64encode(bytes(b)).decode())


def test_untraced_wire_form_is_byte_identical():
    """Legacy-wire interop: a wire event with NO trace id must
    serialize exactly as the pre-tracing form — no extra key in the
    relay dict, no change to the Go-JSON encoding."""
    w = _wire_event(trace_id=0)
    d = w.to_dict()
    assert set(d) == {"Body", "R", "S"}
    assert "_TraceID" not in _relay_json(d)
    # The Go-JSON marshal never includes the trace id, traced or not:
    # consensus identity is untouched.
    assert _wire_event(5).marshal_value() == w.marshal_value()


def test_trace_id_wire_round_trip_and_gojson_compat():
    w = _wire_event(trace_id=42)
    d = w.to_dict()
    assert d["_TraceID"] == 42
    back = WireEvent.from_json_obj(json.loads(_relay_json(d)))
    assert back.trace_id == 42
    assert back.body.index == 4 and int(back.r) == 7
    # Legacy dict (no _TraceID) parses with trace_id 0.
    legacy = {k: v for k, v in d.items() if k != "_TraceID"}
    assert WireEvent.from_json_obj(legacy).trace_id == 0

    # Event-level: the trace id rides to_wire() but never the event's
    # own hash/signature material.
    ev = Event.new([b"payload"], ["", ""], b"\x01" * 32, 0,
                   timestamp=Timestamp(1_700_000_000_000_000_000))
    h0 = ev.hex()
    ev.trace_id = 99
    ev.invalidate()
    assert ev.hex() == h0
    assert ev.to_wire().trace_id == 99


def test_sampling_off_is_noop():
    """trace_sample=0 (the default): no tx is ever stamped, no flow
    entries hit the ring, and the wire events a node serves carry no
    trace ids."""
    nodes = make_traced_nodes(2, trace_sample=0.0)
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        bombard(nodes, 1.5)
        time.sleep(0.5)
        for nd in nodes:
            assert nd._tx_trace_ids == {}
            assert all("flow" not in sp for sp in nd.trace.snapshot())
        with nodes[0].core_lock:
            diff = nodes[0].core.diff({pid: -1 for pid in
                                       nodes[0].core.known()})
            wire = nodes[0].core.to_wire(diff)
        assert wire and all(w.trace_id == 0 for w in wire)
        assert all("_TraceID" not in w.to_dict() for w in wire)
    finally:
        for nd in nodes:
            nd.shutdown()


# --------------------------------------------- flow events + the ring


def test_span_ring_flows_and_since_cursor():
    ring = SpanRing(64)
    with ring.span("tx_submit", cat="tx"):
        ring.flow("s", 7, cat="tx")
    cursor = ring.last_seq
    with ring.span("commit", cat="commit"):
        ring.flow("f", 7, cat="commit")
    # Cursor: only entries completed after `cursor`.
    newer = ring.snapshot(since_seq=cursor)
    assert len(newer) == 2 and any(sp.get("flow") == "f" for sp in newer)
    doc = ring.to_chrome_trace(pid=3, since_seq=cursor)
    phs = [e["ph"] for e in doc["traceEvents"] if e["ph"] != "M"]
    assert phs.count("X") == 1 and phs.count("f") == 1
    assert doc["babble"]["next_since"] == ring.last_seq
    # Full dump: the flow chain s..f with one shared id.
    full = ring.to_chrome_trace(pid=3)
    flows = [e for e in full["traceEvents"] if e["ph"] in ("s", "t", "f")]
    assert {e["id"] for e in flows} == {7}
    assert [e["ph"] for e in flows] == ["s", "f"]
    # Rebase hook shifts ts.
    shifted = ring.to_chrome_trace(pid=3, rebase=lambda t: t + 10**15)
    raw = ring.to_chrome_trace(pid=3)
    x_s = [e for e in shifted["traceEvents"] if e["ph"] == "X"][0]
    x_r = [e for e in raw["traceEvents"] if e["ph"] == "X"][0]
    assert x_s["ts"] - x_r["ts"] == pytest.approx(10**15 / 1000.0)


def test_tracemerge_merges_and_validates():
    """Two rings -> two pids -> one timeline: s/f flow pairs resolve
    across pids, pid collisions are remapped, and per-dump clock
    blocks rebase raw monotonic dumps onto one epoch."""
    a, b = SpanRing(16), SpanRing(16)
    with a.span("tx_submit", cat="tx"):
        a.flow("s", 1234, cat="tx")
    with b.span("sync", cat="sync", batch=3):
        b.flow("t", 1234, cat="sync", hop="recv")
    with a.span("commit", cat="commit"):
        a.flow("f", 1234, cat="commit")
    d0 = a.to_chrome_trace(pid=0, meta={
        "epoch": "mono",
        "clock": {"wall_offset_ns": 5_000_000, "cluster_adjust_ns": 0}})
    d1 = b.to_chrome_trace(pid=1, meta={
        "epoch": "mono",
        "clock": {"wall_offset_ns": 0, "cluster_adjust_ns": 1_000_000}})
    merged = tracemerge.merge([d0, d1])
    assert tracemerge.validate(merged, require_cross_pid_flow=True) == []
    # Clock rebase applied: pid 0 events shifted by 5ms, pid 1 by 1ms.
    x0 = [e for e in merged["traceEvents"]
          if e["ph"] == "X" and e["pid"] == 0][0]
    raw0 = [e for e in d0["traceEvents"] if e["ph"] == "X"][0]
    assert x0["ts"] - raw0["ts"] == pytest.approx(5000.0)
    # pid collision: merging the same dump twice remaps the second.
    twice = tracemerge.merge([d0, json.loads(json.dumps(d0))])
    assert len({e["pid"] for e in twice["traceEvents"]}) == 2
    # Validator catches broken chains.
    bad = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
         "args": {"name": "x"}},
        {"ph": "f", "id": 9, "pid": 0, "tid": 1, "ts": 1.0},
    ]}
    assert any("flow 9" in p for p in tracemerge.validate(bad))


# ----------------------------------------------- live endpoint modes


def test_debug_trace_since_and_epoch_modes():
    nodes = make_traced_nodes(2, trace_sample=1.0)
    service = Service("127.0.0.1:0", nodes[0])
    service.serve_async()
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        bombard(nodes, 2.0)

        def get(url):
            with urllib.request.urlopen(url, timeout=5) as r:
                return json.loads(r.read())

        base = f"http://{service.addr}/debug/trace"
        doc = get(base)
        assert doc["babble"]["epoch"] == "mono"
        assert "clock" in doc["babble"]
        cursor = doc["babble"]["next_since"]
        assert cursor > 0
        n_x = sum(1 for e in doc["traceEvents"] if e["ph"] == "X")
        assert n_x > 0
        # Incremental fetch: everything already seen is excluded.
        doc2 = get(f"{base}?since={cursor}")
        seen = {e["args"].get("span_id") for e in doc["traceEvents"]
                if e["ph"] == "X"}
        again = {e["args"].get("span_id") for e in doc2["traceEvents"]
                 if e["ph"] == "X"}
        assert not (seen & again)
        # Cluster-epoch rebase: timestamps land on wall-clock scale
        # (raw perf_counter is process uptime — orders of magnitude
        # smaller than Unix-epoch microseconds).
        doc3 = get(f"{base}?epoch=cluster")
        xs = [e["ts"] for e in doc3["traceEvents"] if e["ph"] == "X"]
        assert xs and min(xs) > 1e15
        assert doc3["babble"]["epoch"] == "cluster"
    finally:
        for nd in nodes:
            nd.shutdown()
        service.close()


def test_three_node_smoke_traced_tx_spans_two_pids(tmp_path):
    """THE acceptance smoke: a 3-node host-gossip run with sampling on
    produces, via tracemerge over the nodes' /debug/trace dumps, ONE
    Perfetto-loadable timeline in which a sampled transaction's flow
    events span at least two node pids from submit ("s") to
    CommitBlock ("f")."""
    nodes = make_traced_nodes(3, trace_sample=1.0)
    services = [Service("127.0.0.1:0", nd) for nd in nodes]
    for svc in services:
        svc.serve_async()
    try:
        for nd in nodes:
            nd.run_async(gossip=True)

        def merged_doc():
            dumps = [tracemerge.load_dump(
                f"http://{svc.addr}/debug/trace") for svc in services]
            return tracemerge.merge(dumps)

        def has_cross_pid_flow():
            doc = merged_doc()
            return tracemerge.validate(
                doc, require_cross_pid_flow=True) == []

        committed = lambda: min(  # noqa: E731
            len(nd.core.get_consensus_events()) for nd in nodes)
        ok = bombard(
            nodes, 60.0,
            until=lambda: committed() > 30 and has_cross_pid_flow())
        assert ok, "no complete cross-pid flow chain emerged"

        # The CLI does the same end to end: dump files, merge, check.
        paths = []
        for i, svc in enumerate(services):
            doc = tracemerge.load_dump(
                f"http://{svc.addr}/debug/trace")
            p = tmp_path / f"node{i}.json"
            p.write_text(json.dumps(doc))
            paths.append(str(p))
        out = tmp_path / "merged.json"
        rc = tracemerge.main(
            ["--check", "--require-cross-pid-flow", "-o", str(out)]
            + paths)
        assert rc == 0
        merged = json.loads(out.read_text())
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert len(pids) == 3
        # One fully-linked chain: submit somewhere, finish somewhere,
        # >= 2 pids involved.
        chains = {}
        for e in merged["traceEvents"]:
            if e.get("ph") in ("s", "t", "f"):
                chains.setdefault(e["id"], []).append(
                    (e["ph"], e["pid"]))
        complete = [c for c in chains.values()
                    if {p for p, _ in c} >= {"s", "f"}
                    and len({pid for _, pid in c}) >= 2]
        assert complete, f"chains: {list(chains.values())[:5]}"
        # The clock gauges surfaced through /metrics.
        with urllib.request.urlopen(
                f"http://{services[0].addr}/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "babble_clock_offset_ns" in text
        check_gossip(nodes)
    finally:
        for nd in nodes:
            nd.shutdown()
        for svc in services:
            svc.close()


# ------------------------------------------------ bench_compare gate


def _load_bench_compare():
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench_compare.py")
    spec = importlib.util.spec_from_file_location("bench_compare", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_compare_semantics():
    bc = _load_bench_compare()
    baseline = {"metric": "node_events_per_s_smoke",
                "host_events_per_s": 800.0,
                "node_events_per_s": 200.0,
                "commit_latency_p50_ms": 300.0,
                "commit_latency_p99_ms": 500.0}
    # Same machine speed, clean run: ok.
    fresh = dict(baseline)
    rows = bc.compare(fresh, baseline, 0.10)
    by = {r["key"]: r for r in rows}
    assert by["node_events_per_s"]["status"] == "ok"
    assert by["host_events_per_s"]["status"] == "yardstick"
    assert by["commit_latency_p50_ms"]["status"] == "info"  # never gated
    # Half-speed machine, proportional numbers: normalization keeps it
    # green (200 -> 100 ev/s is the machine, not a regression).
    slow = {"metric": baseline["metric"], "host_events_per_s": 400.0,
            "node_events_per_s": 100.0, "commit_latency_p99_ms": 1000.0}
    by = {r["key"]: r for r in bc.compare(slow, baseline, 0.10)}
    assert by["node_events_per_s"]["status"] == "ok"
    assert by["commit_latency_p99_ms"]["status"] == "ok"
    # Real regression on the same machine: caught, direction-aware.
    bad = dict(baseline, node_events_per_s=150.0,
               commit_latency_p99_ms=600.0)
    by = {r["key"]: r for r in bc.compare(bad, baseline, 0.10)}
    assert by["node_events_per_s"]["status"] == "REGRESSION"
    assert by["commit_latency_p99_ms"]["status"] == "REGRESSION"
    # Improvements never fail.
    good = dict(baseline, node_events_per_s=400.0,
                commit_latency_p99_ms=250.0)
    by = {r["key"]: r for r in bc.compare(good, baseline, 0.10)}
    assert by["node_events_per_s"]["status"] == "improved"
    assert by["commit_latency_p99_ms"]["status"] == "improved"
    # gate=False (shape mismatch): informational only.
    by = {r["key"]: r for r in bc.compare(bad, baseline, 0.10,
                                          gate=False)}
    assert by["node_events_per_s"]["status"] == "info"


def test_bench_compare_cli_gate(tmp_path):
    bc = _load_bench_compare()
    base = {"metric": "node_events_per_s_smoke",
            "host_events_per_s": 800.0, "node_events_per_s": 200.0}
    (tmp_path / "BENCH_SMOKE.json").write_text(json.dumps(
        {"parsed": base}))
    full = {"metric": "consensus_events_per_s_n64", "value": 60000.0,
            "host_events_per_s": 800.0}
    against = tmp_path / "BENCH_r05.json"
    against.write_text(json.dumps({"parsed": full}))
    ok = tmp_path / "fresh.json"
    ok.write_text(json.dumps(dict(base, node_events_per_s=195.0)))
    assert bc.main(["--against", str(against), "--fresh", str(ok)]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(dict(base, node_events_per_s=100.0)))
    assert bc.main(["--against", str(against), "--fresh", str(bad)]) == 1
    # Full-bench shape gates straight against --against.
    fullbad = tmp_path / "fullbad.json"
    fullbad.write_text(json.dumps(dict(full, value=40000.0)))
    assert bc.main(
        ["--against", str(against), "--fresh", str(fullbad)]) == 1
