"""Opt-in real-hardware smoke: run the one-shot and incremental engines
on the actual TPU backend (the place the round-2 bench failure lived —
the rest of the suite runs on the virtual CPU mesh and can never catch
a chip-side regression).

Gated behind BABBLE_TPU_TESTS=1 because the chip sits behind a tunnel
that is transiently unavailable; the bench has its own bounded-retry
armor, tests should not flake CI. Run with:

    BABBLE_TPU_TESTS=1 python -m pytest tests/test_tpu_smoke.py -v

The child process is spawned WITHOUT the conftest's forced-CPU
environment so it initializes the real backend.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, sys
sys.path.insert(0, %(repo)r)
import numpy as np
import jax
backend = jax.default_backend()
from babble_tpu.ops.dag import synthetic_dag
from babble_tpu.ops.pipeline import run_pipeline
from babble_tpu.ops.incremental import IncrementalEngine

dag, _ = synthetic_dag(8, 256, seed=0)
rounds, wit, wt, famous, rr, cts = map(np.asarray, run_pipeline(dag))

eng = IncrementalEngine(8, capacity=64, block=64, k_capacity=8)
for k in range(0, 256, 64):
    eng.append_batch(dag.self_parent[k:k+64], dag.other_parent[k:k+64],
                     dag.creator[k:k+64], dag.index[k:k+64],
                     dag.coin[k:k+64], np.arange(k, k+64))
    eng.run()
ok = bool((eng.rounds[:256] == rounds).all() and (eng.rr[:256] == rr).all())
print(json.dumps({"backend": backend, "consensus": int((rr >= 0).sum()),
                  "incremental_parity": ok}))
"""


_CHILD_1024 = r"""
import json, sys
sys.path.insert(0, %(repo)r)
import numpy as np
import jax
backend = jax.default_backend()
from babble_tpu.ops.dag import synthetic_dag
from babble_tpu.ops.pipeline import run_pipeline
from babble_tpu.ops.incremental import IncrementalEngine

n, e, bs = 1024, 20_000, 4096
dag, _ = synthetic_dag(n, e, seed=2)
eng = IncrementalEngine(n, capacity=32768, block=512, k_capacity=64)
k = 0
while k < e:
    hi = min(k + bs, e)
    eng.append_batch(dag.self_parent[k:hi], dag.other_parent[k:hi],
                     dag.creator[k:hi], dag.index[k:hi],
                     dag.coin[k:hi], np.arange(k, hi))
    eng.run()
    # force a real device->host transfer: axon kernel faults only
    # surface at the copy (run() itself pulls, but an engine carry pull
    # double-checks the closure path the packed results don't cover)
    _ = np.asarray(eng._la[0])
    k = hi
rounds, wit, wt, famous, rr, cts = map(np.asarray,
                                       run_pipeline(dag, engine="closure"))
ok = bool((eng.rounds[:e] == rounds).all() and (eng.rr[:e] == rr).all()
          and (eng.witness[:e] == wit).all())
print(json.dumps({"backend": backend, "parity_1024": ok,
                  "max_round": int(rounds.max())}))
"""


def _run_tpu_child(src):
    env = dict(os.environ)
    # undo the conftest's virtual-CPU forcing for the child
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c", src % {"repo": REPO}],
        capture_output=True, text=True, timeout=900, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.skipif(
    os.environ.get("BABBLE_TPU_TESTS") != "1",
    reason="real-TPU smoke is opt-in (BABBLE_TPU_TESTS=1)",
)
def test_engines_on_real_tpu():
    info = _run_tpu_child(_CHILD)
    assert info["backend"] == "tpu", f"expected the real chip, got {info}"
    assert info["consensus"] > 100
    assert info["incremental_parity"], "incremental != one-shot on TPU"


@pytest.mark.skipif(
    os.environ.get("BABBLE_TPU_TESTS") != "1",
    reason="real-TPU smoke is opt-in (BABBLE_TPU_TESTS=1)",
)
def test_incremental_engine_n1024_on_real_tpu():
    """The live-node engine at the north-star validator count, on the
    real chip, with value pulls after every sync (round-3's frontier
    fault only surfaced at device->host transfer). Guards the warning
    removed from IncrementalEngine.__init__ in round 4."""
    info = _run_tpu_child(_CHILD_1024)
    assert info["backend"] == "tpu", f"expected the real chip, got {info}"
    assert info["parity_1024"], "incremental != one-shot at n=1024 on TPU"
