"""Multi-chip parity: the sharded pipeline (ops/sharded.py) must match
the single-device wavefront pipeline bit-for-bit on the 8-device
virtual mesh — rounds, witnesses, witness table, fame, round received,
and consensus timestamps (SURVEY §5 comms plan; the driver re-checks
this via dryrun_multichip)."""

from __future__ import annotations

import warnings

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from babble_tpu.ops.dag import synthetic_dag
from babble_tpu.ops.pipeline import run_pipeline
from babble_tpu.ops.sharded import sharded_pipeline


def _mesh(shape):
    devices = jax.devices()
    assert len(devices) >= 8, "conftest must provision the virtual mesh"
    if shape == "1d":
        return Mesh(np.array(devices[:8]), ("sp",)), "sp"
    # Hosts x chips: shards span both axes — the multi-host layout
    # where XLA routes intra-host collective segments over ICI and
    # cross-host segments over DCN (the reference's TCP backend spans
    # hosts the same way).
    return Mesh(np.array(devices[:8]).reshape(2, 4), ("dcn", "ici")), (
        "dcn", "ici")


@pytest.mark.parametrize(
    "n,e,shape",
    [(8, 400, "1d"), (16, 1000, "1d"), (64, 5000, "1d"), (8, 480, "2d"),
     (64, 5000, "2d")],
    ids=["n8", "n16", "n64-e5000", "n8-dcn-ici", "n64-dcn-ici"],
)
def test_sharded_matches_single_device(n, e, shape):
    mesh, axis = _mesh(shape)
    dag, _ = synthetic_dag(n, e, seed=11)
    ref = [np.asarray(x) for x in run_pipeline(dag, engine="wavefront")]
    got = [np.asarray(x) for x in sharded_pipeline(dag, mesh, axis=axis)]

    names = ["rounds", "witness", "witness_table", "famous",
             "round_received", "cts"]
    for name, a, b in zip(names, ref, got):
        assert a.shape == b.shape, name
        assert (a == b).all(), (
            f"{name} mismatch: {np.argwhere(a != b)[:5]}")


@pytest.mark.parametrize("shape", ["1d", "2d"], ids=["1d", "dcn-ici"])
def test_sharded_incremental_engine(shape):
    """IncrementalEngine with mesh-resident carries (GSPMD-partitioned
    kernels) must match the single-device engine bit-for-bit across
    batched ingest, capacity growth, and chain-bucket growth — and the
    resident carries must be PHYSICALLY partitioned (the memory-scaling
    claim: a node's DAG capacity grows with its chips)."""
    from babble_tpu.ops.incremental import IncrementalEngine

    mesh, axis = _mesh(shape)
    n, e, bs = 16, 1200, 131
    dag, _ = synthetic_dag(n, e, seed=5)

    ref = IncrementalEngine(n, capacity=64, block=64, k_capacity=8)
    eng = IncrementalEngine(n, capacity=64, block=64, k_capacity=8,
                            mesh=mesh, mesh_axis=axis)
    # The mesh engine must select the non-donating kernel twins: under
    # GSPMD the donated growth-concat inputs are frequently unusable
    # (resharded outputs), and XLA would warn on every capacity step.
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        k = 0
        while k < e:
            hi = min(k + bs, e)
            for g in (ref, eng):
                g.append_batch(
                    dag.self_parent[k:hi], dag.other_parent[k:hi],
                    dag.creator[k:hi], dag.index[k:hi], dag.coin[k:hi],
                    np.arange(k, hi))
                g.run()
            k = hi
    donation = [w for w in caught if "donated buffers" in str(w.message)]
    assert not donation, f"XLA donation warnings: {donation[:3]}"

    assert (eng.rounds[:e] == ref.rounds[:e]).all()
    assert (eng.witness[:e] == ref.witness[:e]).all()
    assert (eng.rr[:e] == ref.rr[:e]).all()
    assert (eng.cts_ns[:e] == ref.cts_ns[:e]).all()
    assert (eng.famous == ref.famous).all()
    assert eng.undecided_rounds == ref.undecided_rounds

    # The big carries must be physically partitioned across the mesh.
    d = 8
    for name in ("_la", "_chain_la", "_ranks"):
        arr = getattr(eng, name)
        shards = arr.addressable_shards
        total = int(np.prod(arr.shape))
        per_dev = sorted(int(np.prod(s.data.shape)) for s in shards)
        assert len(per_dev) == d, name
        # Uneven event-axis splits leave the last shard smaller; no
        # shard may hold the whole (replicated) table.
        assert per_dev[-1] < total, f"{name} is replicated, not sharded"
        assert sum(per_dev) == total, name


def test_node_engine_mesh_gossip():
    """A live 4-node testnet whose tpu engines keep their carries
    sharded over a 4-device mesh (Config.engine_mesh / --engine_mesh):
    gossip must converge exactly as with the single-device engine."""
    from test_node import check_gossip, make_nodes, run_gossip

    nodes = make_nodes(4, "inmem", engine="tpu", engine_mesh=4)
    for node in nodes:
        eng = node.core.hg.engine
        assert eng._mesh is not None
        assert len(eng._la.sharding.device_set) == 4
    run_gossip(nodes, target_round=3, timeout=300.0)
    check_gossip(nodes)
