"""Multi-chip parity: the sharded pipeline (ops/sharded.py) must match
the single-device wavefront pipeline bit-for-bit on the 8-device
virtual mesh — rounds, witnesses, witness table, fame, round received,
and consensus timestamps (SURVEY §5 comms plan; the driver re-checks
this via dryrun_multichip)."""

from __future__ import annotations

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from babble_tpu.ops.dag import synthetic_dag
from babble_tpu.ops.pipeline import run_pipeline
from babble_tpu.ops.sharded import sharded_pipeline


def _mesh(shape):
    devices = jax.devices()
    assert len(devices) >= 8, "conftest must provision the virtual mesh"
    if shape == "1d":
        return Mesh(np.array(devices[:8]), ("sp",)), "sp"
    # Hosts x chips: shards span both axes — the multi-host layout
    # where XLA routes intra-host collective segments over ICI and
    # cross-host segments over DCN (the reference's TCP backend spans
    # hosts the same way).
    return Mesh(np.array(devices[:8]).reshape(2, 4), ("dcn", "ici")), (
        "dcn", "ici")


@pytest.mark.parametrize(
    "n,e,shape",
    [(8, 400, "1d"), (16, 1000, "1d"), (64, 5000, "1d"), (8, 480, "2d"),
     (64, 5000, "2d")],
    ids=["n8", "n16", "n64-e5000", "n8-dcn-ici", "n64-dcn-ici"],
)
def test_sharded_matches_single_device(n, e, shape):
    mesh, axis = _mesh(shape)
    dag, _ = synthetic_dag(n, e, seed=11)
    ref = [np.asarray(x) for x in run_pipeline(dag, engine="wavefront")]
    got = [np.asarray(x) for x in sharded_pipeline(dag, mesh, axis=axis)]

    names = ["rounds", "witness", "witness_table", "famous",
             "round_received", "cts"]
    for name, a, b in zip(names, ref, got):
        assert a.shape == b.shape, name
        assert (a == b).all(), (
            f"{name} mismatch: {np.argwhere(a != b)[:5]}")
