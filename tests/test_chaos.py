"""Chaos-injection harness (FaultyTransport) and fault soak tests.

Unit tests pin the decorator's semantics (seeded determinism,
asymmetric partitions, crash gating both legs, duplicate delivery).
The quick convergence test runs in tier-1; the full soak — >=20% drop,
50-200ms jittered delay, an asymmetric partition that heals mid-run,
and a node crash + recovery — is marked slow and carried by the CI
chaos job (PAPER.md's claim under test: same transactions, same order,
on every node, under partial failure)."""

from __future__ import annotations

import queue
import threading
import time

import pytest

from babble_tpu.net import FaultyTransport, InmemTransport, TransportError
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.net.transport import (
    EagerSyncRequest,
    EagerSyncResponse,
    FastForwardResponse,
    SyncRequest,
    SyncResponse,
)
from babble_tpu.hashgraph import InmemStore
from babble_tpu.node import Node
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.proxy import InmemAppProxy

from test_node import check_gossip, make_keyed_peers

CACHE = 10000


# ----------------------------------------------------------- helpers


class _Responder:
    """Drains a transport's consumer queue, answering every RPC —
    a stand-in node for transport-level unit tests."""

    def __init__(self, trans):
        self.trans = trans
        self.stop = threading.Event()
        self.served = {"sync": 0, "eager": 0, "ff": 0}
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        q = self.trans.consumer()
        while not self.stop.is_set():
            try:
                rpc = q.get(timeout=0.05)
            except queue.Empty:
                continue
            cmd = rpc.command
            if isinstance(cmd, SyncRequest):
                self.served["sync"] += 1
                rpc.respond(SyncResponse(0))
            elif isinstance(cmd, EagerSyncRequest):
                self.served["eager"] += 1
                rpc.respond(EagerSyncResponse(0, True))
            else:
                self.served["ff"] += 1
                rpc.respond(FastForwardResponse(0))

    def close(self):
        self.stop.set()
        self.thread.join(timeout=1.0)


def faulty_pair(**faults):
    a_in = InmemTransport("addrA", timeout=1.0)
    b_in = InmemTransport("addrB", timeout=1.0)
    connect_all([a_in, b_in])
    a = FaultyTransport(a_in, seed=7, **faults)
    b = FaultyTransport(b_in, seed=7, **faults)
    return a, b


def make_chaos_nodes(n, seed, heartbeat=0.01, **faults):
    """An n-node inmem net with every node behind a FaultyTransport
    sharing one seed (per-pair rng streams derive from seed+addresses,
    so the whole fabric's fault plan is reproducible)."""
    inner = [InmemTransport(f"addr{i}", timeout=2.0) for i in range(n)]
    connect_all(inner)
    wrapped = {t.local_addr(): FaultyTransport(t, seed=seed, **faults)
               for t in inner}
    entries = make_keyed_peers(n, addr_fn=lambda i: f"addr{i}")
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = fast_config(heartbeat=heartbeat)
        # Tight breaker + retry so injected faults are absorbed fast.
        conf.breaker_threshold = 3
        conf.breaker_base_backoff = 0.2
        conf.breaker_max_backoff = 2.0
        conf.sync_retries = 1
        conf.sync_retry_backoff = 0.02
        store = InmemStore(participants, CACHE)
        node = Node(conf, i, key, peers, store,
                    wrapped[peer.net_addr], InmemAppProxy())
        node.init()
        nodes.append(node)
    return nodes, wrapped


def bombard_until(nodes, target_round, timeout, predicate=lambda: True,
                  submit_to=None):
    """Submit transactions until every node (or `submit_to`) reaches
    target_round AND predicate() holds."""
    active = submit_to if submit_to is not None else nodes
    deadline = time.monotonic() + timeout
    i = 0
    while time.monotonic() < deadline:
        active[i % len(active)].submit_tx(f"chaos tx {i}".encode())
        i += 1
        done = all((n.core.get_last_consensus_round_index() or 0)
                   >= target_round for n in nodes)
        if done and predicate():
            return
        time.sleep(0.02)
    rounds = [n.core.get_last_consensus_round_index() for n in nodes]
    raise AssertionError(
        f"timeout: rounds {rounds} < {target_round} or predicate unmet")


# -------------------------------------------------------------- unit


def test_fault_plan_is_seed_deterministic():
    """Same seed + same endpoints => identical drop decisions at the
    same call indices."""

    def decisions(seed):
        inner = InmemTransport("addrA", timeout=0.2)
        peer = InmemTransport("addrB", timeout=0.2)
        connect_all([inner, peer])
        resp = _Responder(peer)
        t = FaultyTransport(inner, seed=seed, drop=0.5)
        out = []
        for _ in range(40):
            try:
                t.sync("addrB", SyncRequest(0, {}))
                out.append(True)
            except TransportError as exc:
                assert "injected" in str(exc)
                out.append(False)
        resp.close()
        t.close()
        return out

    a, b, c = decisions(123), decisions(123), decisions(99)
    assert a == b
    assert a != c  # different seed, different plan
    assert not all(a) and any(a)  # drops actually happen, not always


def test_partition_is_asymmetric_and_heals():
    a, b = faulty_pair()
    ra, rb = _Responder(a), _Responder(b)
    try:
        a.partition("addrB")
        with pytest.raises(TransportError, match="partitioned"):
            a.sync("addrB", SyncRequest(0, {}))
        # The reverse leg still flows: asymmetric by construction.
        assert isinstance(b.sync("addrA", SyncRequest(0, {})), SyncResponse)
        a.heal()
        assert isinstance(a.sync("addrB", SyncRequest(0, {})), SyncResponse)
    finally:
        ra.close(), rb.close(), a.close(), b.close()


def test_crash_gates_both_legs_and_restores():
    a, b = faulty_pair()
    ra, rb = _Responder(a), _Responder(b)
    try:
        a.crash()
        # Outbound from the crashed box fails...
        with pytest.raises(TransportError, match="crashed"):
            a.sync("addrB", SyncRequest(0, {}))
        # ...and inbound TO it fails fast (answered with an error by
        # the pump, not a silent timeout).
        t0 = time.monotonic()
        with pytest.raises(TransportError, match="crashed"):
            b.sync("addrA", SyncRequest(0, {}))
        assert time.monotonic() - t0 < 0.5
        a.restore()
        assert isinstance(a.sync("addrB", SyncRequest(0, {})), SyncResponse)
        assert isinstance(b.sync("addrA", SyncRequest(0, {})), SyncResponse)
    finally:
        ra.close(), rb.close(), a.close(), b.close()


def test_duplicate_delivers_push_twice():
    a, b = faulty_pair(duplicate=1.0)
    rb = _Responder(b)
    try:
        a.eager_sync("addrB", EagerSyncRequest(0, []))
        time.sleep(0.1)
        assert rb.served["eager"] == 2  # at-least-once delivery
        assert a.injected["duplicate"] == 1
    finally:
        rb.close(), a.close(), b.close()


def test_node_shutdown_during_inflight_gossip():
    """shutdown() while gossip rounds are riding out injected delays:
    no deadlock, and both gossip slots come back (a leaked slot would
    permanently halve the node's gossip budget)."""
    nodes, _ = make_chaos_nodes(3, seed=5, delay_min=0.1, delay_max=0.25)
    for nd in nodes:
        nd.run_async(gossip=True)
    for i in range(20):
        nodes[i % 3].submit_tx(f"tx {i}".encode())
    time.sleep(0.3)  # gossip rounds now in flight inside the delays
    t0 = time.monotonic()
    for nd in nodes:
        nd.shutdown()
    assert time.monotonic() - t0 < 10.0, "shutdown deadlocked"
    for nd in nodes:
        # In-flight rounds release their slots in a finally; both must
        # be recoverable shortly after shutdown.
        assert nd._gossip_slots.acquire(timeout=3.0), "leaked gossip slot"
        assert nd._gossip_slots.acquire(timeout=3.0), "leaked gossip slot"


# ------------------------------------------------------- convergence


def test_chaos_quick_convergence():
    """Tier-1 smoke: 4 nodes under seeded drop/delay/duplicate still
    reach one byte-identical order."""
    nodes, _ = make_chaos_nodes(
        4, seed=2024, drop=0.15, delay_min=0.001, delay_max=0.005,
        duplicate=0.15)
    try:
        for nd in nodes:
            nd.run_async(gossip=True)
        bombard_until(nodes, target_round=5, timeout=90.0)
    finally:
        for nd in nodes:
            nd.shutdown()
    check_gossip(nodes)
    # The plan actually injected faults (the net didn't get lucky).
    total = {}
    for nd in nodes:
        for k, v in nd.trans.injected.items():
            total[k] = total.get(k, 0) + v
    assert total["drop"] > 0 and total["duplicate"] > 0
    # Injected duplicate pushes are visible in the redundancy
    # accounting (docs/observability.md "Gossip efficiency").
    assert sum(nd._m_gossip_agg["duplicate"].value for nd in nodes) > 0
    # Live chain-hash invariant: checked every gossip round under the
    # injected faults, zero false alarms (node/health.py).
    for nd in nodes:
        assert nd.sentinel.divergence_count() == 0, nd.sentinel.reports


def _scrape_metrics(addr):
    """GET /metrics and parse it — a malformed exposition fails the
    soak, exactly like it would fail a real Prometheus scrape."""
    import urllib.request

    from babble_tpu.telemetry import promtext

    with urllib.request.urlopen(f"http://{addr}/metrics", timeout=10) as r:
        assert r.status == 200
        return promtext.parse(r.read().decode())[0]


@pytest.mark.slow
def test_chaos_soak():
    """The acceptance soak (ISSUE 2): 4-node net under >=20% drop,
    50-200ms jittered delay, one asymmetric partition that heals
    mid-run, one node crash + recovery — byte-identical consensus
    order on all nodes, with a fixed seed.

    Telemetry audit (ISSUE 5): /metrics is scraped over real HTTP
    mid-partition and again while node 2 is crashed — the breaker
    gauges and the submit->commit latency tail must REFLECT the
    injected faults, not just exist."""
    from babble_tpu.service import Service
    from babble_tpu.telemetry import promtext

    nodes, faults = make_chaos_nodes(
        4, seed=31337, heartbeat=0.02,
        drop=0.2, delay_min=0.05, delay_max=0.2, duplicate=0.2)
    addr = {i: nodes[i].local_addr for i in range(4)}
    service = Service("127.0.0.1:0", nodes[0])
    service.serve_async()
    breaker_max = 0.0
    try:
        # Phase 1: asymmetric partition 0 -/-> 1 from the start.
        faults[addr[0]].partition(addr[1])
        for nd in nodes:
            nd.run_async(gossip=True)
        bombard_until(nodes, target_round=2, timeout=120.0)

        # Mid-partition scrape: node 0's outbound leg to node 1 has
        # been failing the whole phase, so its breaker series must
        # show activity against that peer.
        samples = _scrape_metrics(service.addr)
        trips = {lb["peer"]: v for lb, v in
                 samples.get("babble_breaker_trips", [])}
        states = [v for _, v in samples.get("babble_breaker_state", [])]
        assert addr[1] in trips, "no breaker series for the partitioned peer"
        breaker_max = max([trips[addr[1]]] + states)
        # Fault injection is visible on the scrape too (process-global
        # registry: the chaos transport's own counters).
        fault_kinds = {lb["kind"] for lb, v in
                       samples["babble_transport_faults_total"] if v > 0}
        assert "partitioned" in fault_kinds

        # Phase 2: heal the partition; crash node 2 (both legs dead).
        faults[addr[0]].heal()
        faults[addr[2]].crash()
        survivors = [nodes[i] for i in (0, 1, 3)]
        bombard_until(survivors, target_round=5, timeout=120.0,
                      submit_to=survivors)

        # Mid-crash scrape: with >=20% drop and 50-200ms injected
        # delay on every RPC, the submit->commit p99 cannot be in the
        # sub-delay range a healthy localhost net shows.
        samples = _scrape_metrics(service.addr)
        lat = promtext.histogram_snapshot(
            samples, "babble_commit_latency_seconds")
        assert lat.count > 0, "no commit-latency samples under chaos"
        p50, p99 = lat.quantile(0.5), lat.quantile(0.99)
        assert 0 < p50 <= p99
        assert p99 >= 0.05, f"p99 {p99}s does not reflect injected delay"
        breaker_max = max(
            [breaker_max]
            + [v for _, v in samples.get("babble_breaker_trips", [])]
            + [v for _, v in samples.get("babble_breaker_state", [])])
        assert breaker_max > 0, (
            "breaker gauges never reflected the partition/crash")

        # Phase 3: node 2 comes back and catches up; everyone must
        # reach the final target together.
        faults[addr[2]].restore()
        bombard_until(nodes, target_round=8, timeout=180.0)
    finally:
        for nd in nodes:
            nd.shutdown()
        service.close()
    check_gossip(nodes)
    injected = {k: sum(f.injected[k] for f in faults.values())
                for k in next(iter(faults.values())).injected}
    assert injected["drop"] > 0
    assert injected["partitioned"] > 0
    assert injected["crashed"] + injected["inbound_crashed"] > 0
    # Divergence sentinel audit (docs/observability.md "Consensus
    # health"): the chain-hash invariant was checked LIVE on every
    # gossip round through the partition, the crash, and the
    # duplicates — it must have been active (blocks hashed, peers
    # compared) and have raised ZERO alarms: drops/delays/partitions
    # reorder delivery, never the committed block stream.
    for nd in nodes:
        assert nd.sentinel is not None
        assert nd.sentinel.chain.index > 0, (
            f"node {nd.id}: sentinel hashed no blocks")
        assert nd.sentinel.divergence_count() == 0, (
            f"node {nd.id} false divergence: {nd.sentinel.reports}")
        assert not nd.sentinel.reports
    compared = sum(
        1 for nd in nodes
        for p in nd.sentinel.peer_progress().values()
        if p["last_agreed_index"] >= 0)
    assert compared > 0, "no cross-node chain comparison ever happened"
    # Gossip efficiency audit (docs/observability.md "Gossip
    # efficiency"): the chaos transport injected duplicate pushes
    # (at-least-once delivery) — the redundancy accounting must have
    # SEEN them as duplicate offered events, closing the loop between
    # fault injection and the new counters. Every offered event lands
    # in exactly one classification bucket.
    assert injected["duplicate"] > 0
    dup_seen = sum(nd._m_gossip_agg["duplicate"].value for nd in nodes)
    assert dup_seen > 0, (
        "injected duplicate pushes never surfaced in "
        "babble_gossip_duplicate_events_total")
    for nd in nodes:
        agg = {k: c.value for k, c in nd._m_gossip_agg.items()}
        assert agg["offered"] == agg["new"] + agg["duplicate"] \
            + agg["stale"], f"node {nd.id} classification leak: {agg}"
