"""CLI end-to-end: keygen/version, and a real localhost testnet
launched purely through `python -m babble_tpu.cli run` subprocesses
with dummy chat clients submitting transactions — the demo testnet in
miniature (reference cmd/babble/main.go + demo/)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(*args, **kw):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "babble_tpu.cli", *args],
        capture_output=True, text=True, timeout=60, env=env, **kw,
    )


def test_version():
    out = run_cli("version")
    assert out.returncode == 0
    assert out.stdout.strip()


def test_keygen(tmp_path):
    datadir = str(tmp_path / "keys")
    out = run_cli("keygen", "--datadir", datadir)
    assert out.returncode == 0
    assert "PublicKey: 0x" in out.stdout
    pem = open(os.path.join(datadir, "priv_key.pem")).read()
    assert "EC PRIVATE KEY" in pem

    # keygen without datadir prints the key
    out2 = run_cli("keygen")
    assert "PRIVATE KEY" in out2.stdout


@pytest.mark.slow
def test_cli_testnet(tmp_path):
    from babble_tpu.dummy import DummyClient

    n = 3
    base_port = 21700 + (os.getpid() % 500) * 10
    datadirs, pubs = [], []
    for i in range(n):
        d = str(tmp_path / f"node{i}")
        out = run_cli("keygen", "--datadir", d)
        assert out.returncode == 0
        pubs.append(out.stdout.split("PublicKey: ")[1].split()[0])
        datadirs.append(d)

    peers = [
        {"NetAddr": f"127.0.0.1:{base_port + i * 3}", "PubKeyHex": pubs[i]}
        for i in range(n)
    ]
    for d in datadirs:
        with open(os.path.join(d, "peers.json"), "w") as f:
            json.dump(peers, f)

    procs, clients = [], []
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        for i in range(n):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "babble_tpu.cli", "run",
                 "--datadir", datadirs[i],
                 "--node_addr", f"127.0.0.1:{base_port + i * 3}",
                 "--proxy_addr", f"127.0.0.1:{base_port + i * 3 + 1}",
                 "--client_addr", f"127.0.0.1:{base_port + i * 3 + 2}",
                 "--service_addr", f"127.0.0.1:{base_port + 1000 + i}",
                 "--heartbeat", "50", "--log_level", "error"],
                env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            ))
        # wait for the app-proxy servers to come up, then attach clients
        import socket

        def wait_port(port, deadline):
            while time.monotonic() < deadline:
                s = socket.socket()
                s.settimeout(0.5)
                try:
                    s.connect(("127.0.0.1", port))
                    return True
                except OSError:
                    time.sleep(0.2)
                finally:
                    s.close()
            return False

        boot_deadline = time.monotonic() + 30
        for i in range(n):
            port_up = wait_port(base_port + i * 3 + 1, boot_deadline)
            assert procs[i].poll() is None and port_up, (
                f"node {i} not up: {procs[i].stderr.read()[-2000:] if procs[i].poll() is not None else 'port closed'}"
            )
            clients.append(DummyClient(
                f"127.0.0.1:{base_port + i * 3 + 1}",
                f"127.0.0.1:{base_port + i * 3 + 2}",
            ))

        # chat: each client submits messages until consensus advances
        deadline = time.monotonic() + 90
        committed = []
        k = 0
        while time.monotonic() < deadline:
            try:
                clients[k % n].submit_tx(f"client{k % n}: msg {k}".encode())
            except OSError:
                pass  # node still warming up; retry next tick
            k += 1
            committed = clients[0].state.get_committed_transactions()
            if len(committed) >= 5:
                break
            time.sleep(0.05)
        assert len(committed) >= 5, "testnet never committed transactions"

        # all clients converge on the same committed prefix
        time.sleep(1.0)
        logs = [c.state.get_committed_transactions() for c in clients]
        m = min(len(log) for log in logs)
        assert m > 0
        for log in logs[1:]:
            assert log[:m] == logs[0][:m]

        # /Stats serves live counters
        with urllib.request.urlopen(
            f"http://127.0.0.1:{base_port + 1000}/Stats", timeout=3
        ) as r:
            stats = json.loads(r.read())
        assert stats["state"] == "Babbling"
        assert int(stats["consensus_transactions"]) > 0
    finally:
        for c in clients:
            c.close()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
