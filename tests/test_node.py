"""Node runtime tests: core-pair syncs (reference node/core_test.go) and
multi-node gossip with checkGossip prefix equality (reference
node/node_test.go:396-599), over both the inmem and TCP transports."""

from __future__ import annotations

import time

import pytest

from babble_tpu import crypto
from babble_tpu.hashgraph import InmemStore
from babble_tpu.net import InmemTransport, Peer, TCPTransport
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.node import Core, Node
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.proxy import InmemAppProxy

CACHE = 10000


def make_keyed_peers(n, seed_base=5000, addr_fn=None):
    keys = [crypto.key_from_seed(seed_base + i) for i in range(n)]
    entries = []
    for i, k in enumerate(keys):
        pub_hex = "0x" + crypto.pub_key_bytes(k).hex().upper()
        addr = addr_fn(i) if addr_fn else f"peer{i}"
        entries.append((k, Peer(addr, pub_hex)))
    # canonical id assignment: sorted pubkey order (cmd/babble/main.go:215-225)
    entries.sort(key=lambda e: e[1].pub_key_hex)
    return entries


def init_cores(n):
    entries = make_keyed_peers(n)
    participants = {p.pub_key_hex: i for i, (_, p) in enumerate(entries)}
    cores = []
    for i, (key, _) in enumerate(entries):
        core = Core(i, key, participants, InmemStore(participants, CACHE))
        core.init()
        cores.append(core)
    return cores


def synchronize_cores(cores, frm, to, payload=()):
    known_by_to = cores[to].known()
    unknown = cores[frm].diff(known_by_to)
    wire = cores[frm].to_wire(unknown)
    cores[to].add_transactions(list(payload))
    cores[to].sync(wire)


def sync_and_run_consensus(cores, frm, to, payload=()):
    synchronize_cores(cores, frm, to, payload)
    cores[to].run_consensus()


# ---------------------------------------------------------------- cores


def test_core_init_heads():
    cores = init_cores(3)
    for c in cores:
        assert c.seq == 0
        assert c.head != ""
        head = c.get_head()
        assert head.creator() == c.hex_id()


def test_core_sync_pair():
    cores = init_cores(2)
    # 0 -> 1: 1 learns 0's initial event and creates a new head
    synchronize_cores(cores, 0, 1, [b"hello"])
    assert cores[1].seq == 1
    known = cores[1].known()
    assert sorted(known.values()) == [0, 1]
    # back: 0 learns 1's two events
    synchronize_cores(cores, 1, 0)
    assert cores[0].seq == 1
    assert all(v == 1 for v in cores[0].known().values())


def test_core_sync_tolerates_duplicates():
    """A sync batch computed against a stale known-map overlaps events
    the receiver already has (pulls and pushes run concurrently in the
    live node); already-known events are skipped, the rest of the batch
    lands, and the receiver's state matches a duplicate-free sync —
    aborting on the first duplicate wedged nodes permanently."""
    cores = init_cores(2)
    synchronize_cores(cores, 0, 1, [b"a"])
    # A stale diff: everything core 0 has, including what core 1
    # already knows (known-map of a fresh peer).
    stale_known = {pid: -1 for pid in cores[1].known()}
    overlap = cores[0].to_wire(cores[0].diff(stale_known))
    assert len(overlap) >= 1
    before = cores[1].known()
    cores[1].sync(overlap)  # must not raise
    after = cores[1].known()
    # Only core 1's own new head event was added; core 0's events were
    # all duplicates and silently skipped.
    for pid, idx in before.items():
        assert after[pid] >= idx
    assert sum(after.values()) == sum(before.values()) + 1
    # State remains insertable: a clean follow-up round-trip works.
    synchronize_cores(cores, 1, 0)
    synchronize_cores(cores, 0, 1)


def test_core_consensus_identical_order():
    """Scripted gossip between 3 cores converges to identical consensus
    order — reference core_test.go TestConsensus:354."""
    cores = init_cores(3)
    playbook = [
        (0, 1, [b"tx one"]),
        (1, 2, []),
        (2, 0, [b"tx two"]),
        (0, 1, []),
        (1, 2, [b"tx three"]),
        (2, 0, []),
        (0, 1, [b"tx four"]),
        (1, 2, []),
        (2, 0, []),
        (0, 1, []),
        (1, 2, []),
        (2, 0, []),
    ]
    for frm, to, payload in playbook:
        sync_and_run_consensus(cores, frm, to, payload)

    lens = [len(c.get_consensus_events()) for c in cores]
    assert max(lens) > 0, "no consensus reached"
    ref = cores[0].get_consensus_events()
    for c in cores[1:]:
        other = c.get_consensus_events()
        m = min(len(ref), len(other))
        assert ref[:m] == other[:m]


def test_core_over_sync_limit():
    cores = init_cores(2)
    for _ in range(5):
        synchronize_cores(cores, 0, 1, [b"x"])
        synchronize_cores(cores, 1, 0)
    known_zero = {i: -1 for i in cores[0].known()}
    assert cores[0].over_sync_limit(known_zero, 5)
    assert not cores[0].over_sync_limit(cores[0].known(), 5)


# ---------------------------------------------------------------- nodes


def make_nodes(n, transport, engine="host", engine_mesh=0):
    if transport == "tcp":
        transports = [
            TCPTransport("127.0.0.1:0", timeout=2.0) for _ in range(n)
        ]
        addrs = [t.local_addr() for t in transports]
        entries = make_keyed_peers(n, addr_fn=lambda i: addrs[i])
    else:
        transports = [InmemTransport(f"addr{i}", timeout=2.0) for i in range(n)]
        connect_all(transports)
        entries = make_keyed_peers(n, addr_fn=lambda i: f"addr{i}")

    # transports were created in creation order; map them to sorted order
    by_addr = {t.local_addr(): t for t in transports}
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}

    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = fast_config(heartbeat=0.01 if transport == "inmem" else 0.05)
        conf.engine = engine
        conf.engine_mesh = engine_mesh
        if engine == "tpu":
            # Production cadence (cli.py default): a dedicated
            # consensus worker batching syncs per device pass, with
            # the core lock released around the device wait — the
            # unlocked seam must be exercised by gossip, not only by
            # the deterministic interleave unit test.
            conf.consensus_interval = 0.05
        store = InmemStore(participants, CACHE)
        proxy = InmemAppProxy()
        node = Node(conf, i, key, peers, store, by_addr[peer.net_addr], proxy)
        node.init()
        nodes.append(node)
    return nodes


def run_gossip(nodes, target_round, timeout=60.0, shutdown=True):
    """Run all nodes and bombard them with transactions until every
    node reaches target_round — the reference's gossip/bombardAndWait
    driver (node_test.go:507-545,601-617). Continuous submission
    matters: nodes go quiescent by design when nothing is pending.
    shutdown=False leaves the testnet running (reference gossip()'s
    shutdown flag, node_test.go:507)."""
    for node in nodes:
        node.run_async(gossip=True)
    submitted = []
    deadline = time.monotonic() + timeout
    i = 0
    try:
        while time.monotonic() < deadline:
            tx = f"node{i % len(nodes)} transaction {i}".encode()
            nodes[i % len(nodes)].submit_tx(tx)
            submitted.append(tx)
            i += 1
            done = all(
                (n.core.get_last_consensus_round_index() or 0) >= target_round
                for n in nodes
            )
            if done:
                return submitted
            time.sleep(0.02)
        rounds = [n.core.get_last_consensus_round_index() for n in nodes]
        raise AssertionError(f"timeout: consensus rounds {rounds} < {target_round}")
    finally:
        if shutdown:
            for node in nodes:
                node.shutdown()


def check_gossip(nodes):
    cons_events = {n.id: n.core.get_consensus_events() for n in nodes}
    cons_txs = {n.id: n.core.get_consensus_transactions() for n in nodes}

    min_e = min(len(v) for v in cons_events.values())
    min_t = min(len(v) for v in cons_txs.values())
    assert min_e > 0, "no consensus events"

    ref_e = cons_events[nodes[0].id]
    ref_t = cons_txs[nodes[0].id]
    for n in nodes[1:]:
        assert cons_events[n.id][:min_e] == ref_e[:min_e], (
            f"consensus event mismatch vs node {n.id}"
        )
        assert cons_txs[n.id][:min_t] == ref_t[:min_t], (
            f"consensus tx mismatch vs node {n.id}"
        )


@pytest.mark.parametrize("transport", ["inmem", "tcp"])
def test_gossip(transport):
    # inmem runs to round 50 like the reference's TestGossip
    # (node_test.go:396-407); tcp keeps a shallower target so the
    # socket path stays covered without doubling suite time.
    target = 50 if transport == "inmem" else 10
    nodes = make_nodes(4, transport)
    run_gossip(nodes, target_round=target, timeout=180.0)
    check_gossip(nodes)


def test_gossip_consensus_interval():
    """Rate-limited consensus (consensus_interval > 0): gossip inserts
    at wire speed, consensus passes batch several syncs, and the
    network still converges to the same order (the trailing heartbeat
    pass drains the backlog when gossip quiesces)."""
    nodes = make_nodes(4, "inmem")
    for node in nodes:
        node.conf.consensus_interval = 0.05
    run_gossip(nodes, target_round=10)
    check_gossip(nodes)


def test_missing_node_gossip():
    """Gossip converges even when one node never participates —
    reference node_test.go:409-420."""
    nodes = make_nodes(4, "inmem")
    try:
        for node in nodes[1:]:
            node.run_async(gossip=True)
        deadline = time.monotonic() + 60.0
        i = 0
        while time.monotonic() < deadline:
            nodes[1 + i % 3].submit_tx(f"tx {i}".encode())
            i += 1
            if all(
                (n.core.get_last_consensus_round_index() or 0) >= 5
                for n in nodes[1:]
            ):
                break
            time.sleep(0.02)
        else:
            raise AssertionError("timeout")
    finally:
        for node in nodes:
            node.shutdown()
    check_gossip(nodes[1:])


def test_stats():
    nodes = make_nodes(4, "inmem")
    run_gossip(nodes, target_round=3)
    stats = nodes[0].get_stats()
    base = {
        "last_consensus_round", "consensus_events", "consensus_transactions",
        "undetermined_events", "transaction_pool", "num_peers", "sync_rate",
        "events_per_second", "rounds_per_second", "round_events", "id", "state",
    }
    assert base <= set(stats)
    assert int(stats["last_consensus_round"]) >= 3
    assert int(stats["num_peers"]) == 3
    assert float(stats["events_per_second"]) > 0
    # per-phase ns timers (reference node/core.go:277-296 phase logging)
    for phase in ("diff", "sync", "run_consensus"):
        last, avg = stats[f"time_{phase}_ns"].split(";avg=")
        assert int(last) > 0 and int(avg) > 0


def test_committed_transactions_reach_proxy():
    nodes = make_nodes(4, "inmem")
    submitted = run_gossip(nodes, target_round=8)
    # every node's app proxy saw a prefix-consistent committed tx stream
    time.sleep(0.2)
    committed = [n.proxy.committed_transactions() for n in nodes]
    assert any(len(c) > 0 for c in committed), "nothing committed to apps"
    for c in committed:
        for tx in c:
            assert tx in submitted


def test_sync_limit():
    """A SyncRequest whose known map trails by more than sync_limit gets
    SyncLimit=true instead of a diff, and the requester passes through
    CatchingUp (whose fast-forward is a reference-parity stub that drops
    back to Babbling) — reference node_test.go:422-459."""
    from babble_tpu.net.transport import SyncRequest
    from babble_tpu.node.state import NodeState

    nodes = make_nodes(2, "inmem")
    try:
        # node 1 serves RPCs but does not gossip; node 0 stays un-run so
        # its state transitions can be observed synchronously.
        nodes[1].run_async(gossip=False)
        for k in range(8):  # node 1 builds a backlog beyond the limit
            nodes[1].core.add_transactions([f"tx {k}".encode()])
            nodes[1].core.add_self_event()

        # Serve-side: an empty-known request gets SyncLimit=true and no
        # events once the backlog exceeds the limit.
        nodes[1].conf.sync_limit = 5
        behind = {i: -1 for i in range(2)}
        resp = nodes[0].trans.sync(
            nodes[1].local_addr, SyncRequest(nodes[0].id, behind))
        assert resp.sync_limit, "expected SyncLimit=true for a lagging peer"
        assert not resp.events

        # Request-side: a pull that hits the limit drives the node into
        # CatchingUp; the run loop's fast-forward (reference-parity
        # stub, node/node.go:432-441) drops back to Babbling.
        nodes[0].conf.sync_limit = 5
        nodes[0]._gossip(nodes[1].local_addr)
        assert nodes[0].state.get_state() == NodeState.CATCHING_UP
        nodes[0]._fast_forward()
        assert nodes[0].state.get_state() == NodeState.BABBLING
    finally:
        for node in nodes:
            node.shutdown()


def test_not_ready_rpc_matches_request_type():
    """A node that is not BABBLING must answer each RPC with the
    response type its caller expects — a SyncResponse to an EagerSync
    caller dies on the response-type check instead of surfacing the
    real 'not ready' error."""
    from babble_tpu.net.transport import (
        EagerSyncRequest,
        EagerSyncResponse,
        FastForwardRequest,
        FastForwardResponse,
        RPC,
        SyncRequest,
        SyncResponse,
    )
    from babble_tpu.node.state import NodeState

    nodes = make_nodes(2, "inmem")
    try:
        nodes[0].state.set_state(NodeState.CATCHING_UP)
        for cmd, expected in (
            (SyncRequest(1, {}), SyncResponse),
            (EagerSyncRequest(1, []), EagerSyncResponse),
            (FastForwardRequest(1), FastForwardResponse),
        ):
            rpc = RPC(cmd)
            nodes[0]._process_rpc(rpc)
            out = rpc.resp_chan.get(timeout=1.0)
            assert isinstance(out.response, expected), (
                f"{type(cmd).__name__} answered with "
                f"{type(out.response).__name__}")
            assert out.error is not None and "not ready" in str(out.error)
    finally:
        for node in nodes:
            node.shutdown()


def test_fast_forward_failure_drops_back_to_babbling():
    """CatchingUp resilience: a garbage frame from the peer, or the
    transport raising mid fast-forward, must drop the node back to
    BABBLING with gossip still functional — not wedge it in
    CatchingUp."""
    from babble_tpu.net.transport import FastForwardResponse, TransportError
    from babble_tpu.node.state import NodeState

    nodes = make_nodes(2, "inmem")
    try:
        nodes[1].run_async(gossip=False)  # serves RPCs

        # Peer returns a garbage frame: deserialization blows up.
        nodes[0].trans.fast_forward = lambda target, args: \
            FastForwardResponse(1, roots={}, events=[{"garbage": 1}])
        nodes[0].state.set_state(NodeState.CATCHING_UP)
        nodes[0]._fast_forward()
        assert nodes[0].state.get_state() == NodeState.BABBLING
        assert nodes[0].fast_forwards == 0

        # Transport raises mid-flight.
        def raising_ff(target, args):
            raise TransportError("injected mid-flight failure")

        nodes[0].trans.fast_forward = raising_ff
        nodes[0].state.set_state(NodeState.CATCHING_UP)
        nodes[0]._fast_forward()
        assert nodes[0].state.get_state() == NodeState.BABBLING

        # Still fully functional: a normal gossip round succeeds.
        before = nodes[0].sync_requests
        nodes[0]._gossip(nodes[1].local_addr)
        assert nodes[0].sync_requests > before
        assert nodes[0].state.get_state() == NodeState.BABBLING
    finally:
        for node in nodes:
            node.shutdown()


def test_shutdown():
    """Shutting a node down closes its transport (peers' syncs fail) and
    the second shutdown is idempotent — reference node_test.go:461-475."""
    from babble_tpu.net.transport import SyncRequest
    from babble_tpu.node.state import NodeState

    nodes = make_nodes(2, "inmem")
    try:
        for node in nodes:
            node.run_async(gossip=True)
        time.sleep(0.2)
        nodes[0].shutdown()
        assert nodes[0].state.get_state() == NodeState.SHUTDOWN

        with pytest.raises(Exception):
            nodes[1].trans.sync(
                nodes[0].local_addr, SyncRequest(nodes[1].id, {0: -1, 1: -1}))

        nodes[1].shutdown()
        assert nodes[1].state.get_state() == NodeState.SHUTDOWN
        nodes[1].shutdown()  # idempotent
    finally:
        for node in nodes:
            node.shutdown()
