"""Host ingest fast path (docs/ingest.md): batched wire materialize /
pooled signature verify outside the core lock / serial-identical insert,
the Event marshal-hash cache-invalidation contract, and the O(Δ) diff
merge."""

from __future__ import annotations

import json
import threading

import pytest

from babble_tpu import crypto
from babble_tpu.hashgraph.event import Event, WireEvent, event_from_json_obj
from babble_tpu.hashgraph.graph import InsertError

from test_node import init_cores, make_nodes, synchronize_cores


# ------------------------------------------------------------ event caches


def test_event_marshal_and_hash_are_cached_and_exact():
    """The memoized encodings must be byte-identical to a fresh
    marshal (consensus order hangs off these bytes)."""
    key = crypto.key_from_seed(42)
    ev = Event.new([b"tx"], ["", ""], crypto.pub_key_bytes(key), 0)
    ev.sign(key)

    m1 = ev.marshal()
    assert ev.marshal() is m1  # memo hit
    # Round-trip through the JSON form and re-marshal: byte-identical.
    clone = event_from_json_obj(json.loads(m1))
    assert clone.marshal() == m1
    assert clone.hex() == ev.hex()
    # Body bytes likewise.
    assert ev.body.marshal() == clone.body.marshal()
    assert ev.body.hash() == clone.body.hash()


def test_event_sign_invalidates_identity_but_not_body():
    key = crypto.key_from_seed(43)
    ev = Event.new([b"tx"], ["", ""], crypto.pub_key_bytes(key), 0)
    ev.sign(key)
    h1, m1, bh1 = ev.hex(), ev.marshal(), ev.body.hash()
    assert ev.verify()

    # Re-sign with a DIFFERENT key: R/S change, so the event hash and
    # marshal must be recomputed — and the memoized verify verdict must
    # flip (the new signature does not match the creator in the body).
    other = crypto.key_from_seed(44)
    ev.sign(other)
    assert ev.hex() != h1
    assert ev.marshal() != m1
    assert not ev.verify()
    # The body was untouched: its memo must still be valid and equal.
    assert ev.body.hash() == bh1


def test_event_mutation_after_hashing_requires_invalidate():
    """Regression for the cache-invalidation contract: a by-hand body
    mutation after hashing goes stale until invalidate(); after
    invalidate() every memo (body bytes, event bytes, hash, hex,
    signature verdict) recomputes from the mutated fields."""
    key = crypto.key_from_seed(45)
    ev = Event.new([b"tx"], ["", ""], crypto.pub_key_bytes(key), 0)
    ev.sign(key)
    h1, bh1 = ev.hex(), ev.body.hash()
    assert ev.verify()

    ev.body.index = 7  # by-hand mutation, no invalidate yet
    assert ev.hex() == h1  # memo is (documented as) stale

    ev.invalidate()
    assert ev.hex() != h1
    assert ev.body.hash() != bh1
    assert not ev.verify()  # signature covered the OLD body bytes

    # Restore and re-invalidate: memos must converge back.
    ev.body.index = 0
    ev.invalidate()
    assert ev.hex() == h1
    assert ev.body.hash() == bh1
    assert ev.verify()


def test_set_wire_info_refreshes_wire_form_only():
    key = crypto.key_from_seed(46)
    ev = Event.new([b"tx"], ["", ""], crypto.pub_key_bytes(key), 0)
    ev.sign(key)
    h1 = ev.hex()
    ev.set_wire_info(3, 1, 5, 2)
    w1 = ev.to_wire()
    assert ev.to_wire() is w1  # memo hit
    assert (w1.body.self_parent_index, w1.body.other_parent_creator_id,
            w1.body.other_parent_index, w1.body.creator_id) == (3, 1, 5, 2)

    ev.set_wire_info(4, 0, 6, 2)
    w2 = ev.to_wire()
    assert w2 is not w1
    assert (w2.body.self_parent_index, w2.body.other_parent_index) == (4, 6)
    # Wire ints are unexported in Go: the identity must NOT move.
    assert ev.hex() == h1


# ------------------------------------------------------------ batched sync


def _ping_pong(cores, rounds, payload=b"x"):
    for k in range(rounds):
        synchronize_cores(cores, 0, 1, [payload + str(k).encode()])
        synchronize_cores(cores, 1, 0)


def test_batched_sync_matches_serial_reference():
    """The batch pipeline (read_wire_batch + pooled verify + insert)
    must land the exact store state the serial per-event loop lands."""
    cores = init_cores(3)
    _ping_pong(cores, 6)

    stale = {pid: -1 for pid in cores[2].known()}
    diff = cores[0].diff(stale)
    wire = cores[0].to_wire(diff)
    assert len(wire) > 10
    expected_other_head = diff[-1].hex()

    # Batch path.
    cores[2].sync(wire)
    batch_known = cores[2].known()
    assert cores[2].get_head().other_parent() == expected_other_head

    # Serial reference: the same playbook on fresh cores (hashes differ
    # — timestamps — but the per-participant index frontier the serial
    # loop lands is deterministic and must match exactly).
    ref = init_cores(3)
    _ping_pong(ref, 6)
    wire_ref = ref[0].to_wire(ref[0].diff(stale))
    assert len(wire_ref) == len(wire)
    for we in wire_ref:
        ev = ref[2].hg.read_wire_info(we)
        if not ref[2].hg.store.has_event(ev.hex()):
            ref[2].insert_event(ev, False)
    self_pid = ref[2].participants[ref[2].hex_id()]
    for pid, idx in ref[2].known().items():
        if pid != self_pid:
            assert batch_known[pid] == idx
    # The batch core additionally wrapped the sync in a self-event.
    assert batch_known[self_pid] == ref[2].known()[self_pid] + 1


def test_sync_head_selection_with_duplicate_tail():
    """Satellite pin: `other_head` must name the LAST wire event of the
    batch even when that event is skipped as a duplicate (overlapping
    pushes/pulls routinely deliver a batch whose tail already landed,
    and whose stored copy may differ in wire indexes — the hash covers
    only {Body, R, S}, so the duplicate's hex still names the stored
    copy), and the follow-up self-event must insert cleanly against
    it."""
    cores = init_cores(2)
    synchronize_cores(cores, 0, 1, [b"a"])
    synchronize_cores(cores, 1, 0)

    stale = {pid: -1 for pid in cores[1].known()}
    wire = cores[0].to_wire(cores[0].diff(stale))
    expected_head = None

    # First overlap push: inserts whatever was missing.
    cores[1].sync(wire)
    # Second identical push: EVERY event is now a duplicate (fresh
    # WireEvent wrappers so the sender's memoized wire forms stay
    # untouched).
    dup = [
        WireEvent(we.body, int(we.r), int(we.s))
        for we in wire
    ]
    last = cores[1].hg.read_wire_batch(dup)[-1]
    expected_head = last.hex()
    assert cores[1].hg.store.has_event(expected_head)

    before_seq = cores[1].seq
    cores[1].sync(dup)  # all duplicates; must not raise
    assert cores[1].seq == before_seq + 1
    head = cores[1].get_head()
    assert head.other_parent() == expected_head


def test_batch_verify_failure_matches_serial_outcome():
    """One bad signature inside a 100-event batch: the prefix before
    the bad event inserts, the bad event raises the serial path's
    InsertError at the same position, nothing after it lands, and the
    store stays consistent (a clean retry batch applies)."""
    cores = init_cores(3)
    _ping_pong(cores, 50)

    stale = {pid: -1 for pid in cores[2].known()}
    diff = cores[0].diff(stale)
    wire = cores[0].to_wire(diff)
    assert len(wire) >= 100
    bad_at = len(wire) // 2
    # Corrupt the signature of one mid-batch event on a COPY (the
    # originals are memoized on the sender's events).
    tampered = list(wire)
    tampered[bad_at] = WireEvent(
        wire[bad_at].body, int(wire[bad_at].r) ^ 1, int(wire[bad_at].s))

    head_before = cores[2].head
    seq_before = cores[2].seq
    with pytest.raises(InsertError, match="Invalid signature"):
        cores[2].sync(tampered)

    # Serial reference: replay the same tampered batch event-by-event.
    ref = init_cores(3)
    _ping_pong(ref, 50)
    ref_wire = list(ref[0].to_wire(ref[0].diff(stale)))
    ref_wire[bad_at] = WireEvent(
        ref_wire[bad_at].body, int(ref_wire[bad_at].r) ^ 1,
        int(ref_wire[bad_at].s))
    with pytest.raises(InsertError, match="Invalid signature"):
        for we in ref_wire:
            ev = ref[2].hg.read_wire_info(we)
            if not ref[2].hg.store.has_event(ev.hex()):
                ref[2].insert_event(ev, False)

    # Identical damage: same per-participant tips, no self-event, head
    # untouched.
    assert cores[2].known() == ref[2].known()
    assert cores[2].head == head_before
    assert cores[2].seq == seq_before

    # Store left consistent: the clean batch still applies fully.
    cores[2].sync(wire)
    for pid, idx in cores[0].known().items():
        if pid != cores[2].participants[cores[2].hex_id()]:
            assert cores[2].known()[pid] == idx


@pytest.mark.parametrize(
    "backend", ["pure-python", "openssl-ctypes", "device-p256"])
def test_batch_verify_failure_position_per_backend(backend, monkeypatch):
    """Cross-backend failure-position parity (docs/ingest.md "Crypto
    plane"): whichever backend fills the batch's signature memos, a
    signature corrupted at batch position k must surface as the serial
    path's InsertError at the same position — prefix inserted, nothing
    after, head untouched."""
    from babble_tpu.crypto import _fallback as fb

    if backend == "pure-python":
        fn = fb.verify_batch
    elif backend == "openssl-ctypes":
        from babble_tpu.crypto import _openssl as ossl

        if not ossl.available():
            pytest.skip("system libcrypto not loadable")
        fn = ossl.verify_batch
    else:
        jax = pytest.importorskip("jax")  # noqa: F841
        from babble_tpu.ops import p256

        fn = p256.verify_batch

    import babble_tpu.node.ingest as ingest

    monkeypatch.setattr(ingest.crypto, "verify_batch", fn)

    cores = init_cores(3)
    _ping_pong(cores, 4)
    stale = {pid: -1 for pid in cores[2].known()}
    # A topological-order prefix is parent-closed; 8 events keep the
    # device kernel on its single compiled 8-lane ladder.
    wire = cores[0].to_wire(cores[0].diff(stale))[:8]
    assert len(wire) == 8
    bad_at = 5
    tampered = list(wire)
    tampered[bad_at] = WireEvent(
        wire[bad_at].body, int(wire[bad_at].r) ^ 1, int(wire[bad_at].s))

    head_before, seq_before = cores[2].head, cores[2].seq
    with pytest.raises(InsertError, match="Invalid signature"):
        cores[2].sync(tampered)

    # Serial reference on a fresh replica of the same playbook.
    ref = init_cores(3)
    _ping_pong(ref, 4)
    ref_wire = list(ref[0].to_wire(ref[0].diff(stale))[:8])
    ref_wire[bad_at] = WireEvent(
        ref_wire[bad_at].body, int(ref_wire[bad_at].r) ^ 1,
        int(ref_wire[bad_at].s))
    with pytest.raises(InsertError, match="Invalid signature"):
        for we in ref_wire:
            ev = ref[2].hg.read_wire_info(we)
            if not ref[2].hg.store.has_event(ev.hex()):
                ref[2].insert_event(ev, False)

    assert cores[2].known() == ref[2].known()
    assert cores[2].head == head_before
    assert cores[2].seq == seq_before


def test_bad_push_feeds_breaker_same_as_serial():
    """A tampered eager-sync batch must surface as a failed push to the
    sender — the outcome the peer's circuit breaker is fed — exactly
    like the serial path's per-event failure did."""
    from babble_tpu.net.transport import EagerSyncRequest, RPC

    nodes = make_nodes(2, "inmem")
    try:
        synchronize_cores([nodes[0].core, nodes[1].core], 0, 1, [b"t"])
        stale = {pid: -1 for pid in nodes[0].core.known()}
        wire = list(nodes[1].core.to_wire(nodes[1].core.diff(stale)))
        # Find a non-duplicate tail event to corrupt.
        tampered = wire[:-1] + [
            WireEvent(wire[-1].body, int(wire[-1].r) ^ 1, int(wire[-1].s))]

        rpc = RPC(EagerSyncRequest(nodes[1].id, tampered))
        nodes[0]._process_rpc(rpc)
        out = rpc.resp_chan.get(timeout=2.0)
        assert out.error is not None
        assert out.response.success is False
    finally:
        for n in nodes:
            n.shutdown()


# ------------------------------------------------ verify outside the lock


def test_verify_runs_outside_core_lock(monkeypatch):
    """Acceptance pin: while a sync batch's signature verification is
    in flight, the core lock is free — a concurrent thread can take it
    and make progress (serve known(), accept an insert)."""
    from babble_tpu.net.transport import EagerSyncRequest, RPC
    import babble_tpu.node.core as core_mod

    nodes = make_nodes(2, "inmem")
    started = threading.Event()
    release = threading.Event()
    real_verify = core_mod.verify_events

    def blocking_verify(events, workers, device_verify=False,
                        runtime="threads"):
        started.set()
        assert release.wait(timeout=10.0), "verify window never released"
        real_verify(events, workers, device_verify, runtime=runtime)

    monkeypatch.setattr(core_mod, "verify_events", blocking_verify)
    try:
        # Something for node0 to ingest from node1.
        nodes[1].core.add_transactions([b"payload"])
        nodes[1].core.add_self_event()
        known0 = nodes[0].core.known()
        wire = nodes[1].core.to_wire(nodes[1].core.diff(known0))
        assert wire

        rpc = RPC(EagerSyncRequest(nodes[1].id, wire))
        t = threading.Thread(
            target=nodes[0]._process_rpc, args=(rpc,), daemon=True)
        t.start()
        assert started.wait(timeout=10.0), "verify never started"

        # The verify batch is in flight — the core lock must be free.
        got = nodes[0].core_lock.acquire(timeout=2.0)
        assert got, "core lock held during signature verification"
        try:
            # Concurrent sync progress under the lock.
            snapshot = nodes[0].core.known()
            assert snapshot is not None
        finally:
            nodes[0].core_lock.release()

        release.set()
        out = rpc.resp_chan.get(timeout=10.0)
        t.join(timeout=5.0)
        assert out.error is None
        assert out.response.success is True
        # The batch actually landed, and the ingest stage timers ran.
        for phase in ("from_wire", "verify", "insert", "sync"):
            assert nodes[0].core.phase_ns[phase][2] >= 1, phase
        stats = nodes[0].get_stats()
        assert "time_verify_ns" in stats
    finally:
        release.set()
        for n in nodes:
            n.shutdown()


# ------------------------------------------------------------- O(Δ) diff


def test_diff_merge_matches_fetch_and_sort():
    """The per-participant-suffix merge must reproduce the old
    implementation (get_event per hash + global topo sort) exactly."""
    cores = init_cores(3)
    for k in range(5):
        synchronize_cores(cores, 0, 1, [b"p" + bytes([k])])
        synchronize_cores(cores, 1, 2)
        synchronize_cores(cores, 2, 0)

    for known in (
        {pid: -1 for pid in cores[0].known()},
        cores[1].known(),
        cores[2].known(),
    ):
        got = [e.hex() for e in cores[0].diff(known)]
        want = []
        for pid, ct in known.items():
            pk = cores[0].reverse_participants[pid]
            for ehex in cores[0].hg.store.participant_events(pk, ct):
                want.append(cores[0].hg.store.get_event(ehex))
        want.sort(key=lambda e: e.topological_index)
        assert got == [e.hex() for e in want]


def test_file_store_participant_event_objects_falls_back_to_db(tmp_path):
    """A freshly reloaded FileStore has empty rolling windows; the
    O(Δ) object feed must serve the suffix from sqlite with topological
    indexes intact."""
    from babble_tpu.hashgraph import FileStore, Hashgraph

    keys = [crypto.key_from_seed(7000 + i) for i in range(2)]
    pubs = [crypto.pub_key_bytes(k) for k in keys]
    participants = {"0x" + p.hex().upper(): i for i, p in enumerate(pubs)}
    path = str(tmp_path / "store.db")
    store = FileStore(participants, 100, path)
    hg = Hashgraph(participants, store)

    heads = {0: "", 1: ""}
    for i in range(4):
        c = i % 2
        ev = Event.new([b"t%d" % i], [heads[c], heads[1 - c]],
                       pubs[c], i // 2)
        ev.sign(keys[c])
        hg.insert_event(ev, True)
        heads[c] = ev.hex()
    store.close()

    reloaded = FileStore.load(100, path)
    for pk in participants:
        objs = reloaded.participant_event_objects(pk, -1)
        assert [e.hex() for e in objs] == reloaded.participant_events(pk, -1)
        assert all(
            a.topological_index < b.topological_index
            for a, b in zip(objs, objs[1:]))
    reloaded.close()


def test_read_wire_batch_resolves_in_batch_parents():
    """A batch's later events name earlier ones as parents; the batch
    materializer must resolve those WITHOUT any store insert in
    between, identically to the interleaved serial path. Core 2 has
    never seen cores 0/1's chain, so nearly every parent coordinate in
    the batch points into the batch itself."""
    cores = init_cores(3)
    synchronize_cores(cores, 0, 1, [b"a"])
    synchronize_cores(cores, 1, 0, [b"b"])
    synchronize_cores(cores, 0, 1, [b"c"])

    known2 = cores[2].known()
    wire = cores[0].to_wire(cores[0].diff(known2))
    assert len(wire) >= 4

    # Materialize first (read_wire_batch does not touch the store)...
    batch = cores[2].hg.read_wire_batch(wire)
    # ...then run the interleaved serial path on the SAME core.
    serial = []
    for we in wire:
        ev = cores[2].hg.read_wire_info(we)
        serial.append(ev)
        if not cores[2].hg.store.has_event(ev.hex()):
            cores[2].insert_event(ev, False)
    assert [e.hex() for e in batch] == [e.hex() for e in serial]
    assert [e.body.parents for e in batch] == [e.body.parents for e in serial]
