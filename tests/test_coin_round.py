"""Coin-round fame decisions — host and device must agree, and the coin
value must be observably load-bearing.

The reference decides fame through a coin flip when a vote round hits
diff % n == 0 without a supermajority tally: each voter adopts the
middle bit of its own hash (hashgraph.go:695-709, middleBit 1039-1048).
Real coin bits depend on event signatures (Event.Hash covers R/S,
event.go:170-180), which are not deterministic across builds — so these
tests force the coin to each constant and assert the
topology-determined outcomes of both worlds. A sign flip anywhere in
the coin path (middle_bit itself, the host's coin-round vote, or the
device kernel's `coin_vote`) swaps or breaks one of the worlds.
"""

from __future__ import annotations

import copy

import pytest

import babble_tpu.hashgraph.graph as graph_mod
import babble_tpu.hashgraph.tpu_graph as tpu_mod
from babble_tpu.hashgraph import InmemStore
from babble_tpu.hashgraph.graph import middle_bit
from babble_tpu.hashgraph.round_info import Trilean
from babble_tpu.hashgraph.tpu_graph import TpuHashgraph

from fixtures import build_coin_graph

CACHE = 10000


def test_middle_bit_vectors():
    """Pin the coin function itself (reference hashgraph.go:1039-1048:
    False iff the middle byte of the hash is zero)."""
    assert middle_bit("0x00") is False
    assert middle_bit("0x" + "AB" * 16 + "00" + "AB" * 15) is False
    assert middle_bit("0x" + "00" * 16 + "80" + "00" * 15) is True
    assert middle_bit("0x" + "FF" * 32) is True


@pytest.fixture(scope="module")
def coin_builder():
    return build_coin_graph()


def _host_run(b, const):
    events = copy.deepcopy(b.ordered_events)
    h = b.make_hashgraph(InmemStore(b.participants(), CACHE))
    for ev in events:
        h.insert_event(ev, True)
    calls = []

    def forced(hx):
        calls.append(hx)
        return bool(const)

    orig = graph_mod.middle_bit
    graph_mod.middle_bit = forced
    try:
        h.divide_rounds()
        h.decide_fame()
        h.find_order()
    finally:
        graph_mod.middle_bit = orig
    return h, calls


def _device_run(b, const):
    events = copy.deepcopy(b.ordered_events)
    t = TpuHashgraph(b.participants(), InmemStore(b.participants(), CACHE),
                     capacity=64, block=64)
    orig_t, orig_g = tpu_mod.middle_bit, graph_mod.middle_bit
    tpu_mod.middle_bit = lambda hx: bool(const)
    graph_mod.middle_bit = lambda hx: bool(const)
    try:
        for ev in events:
            t.insert_event(ev, True)
        t.run_consensus()
    finally:
        tpu_mod.middle_bit = orig_t
        graph_mod.middle_bit = orig_g
    return t


def test_coin_true_world_decides_through_coin(coin_builder):
    """Coin forced to 1: round-4 voters flip coins for w00 (two voters
    lack a supermajority tally at diff=4), and round 5 decides w00
    famous from those coin votes."""
    b = coin_builder
    h, calls = _host_run(b, 1)
    assert len(calls) == 2, "expected exactly two coin votes"
    # the coin voters are round-4 witnesses voting about round 0
    assert sorted(h.round(y) for y in calls) == [4, 4]
    r0 = h.store.get_round(0)
    assert r0.events[b.index["w00"]].famous == Trilean.TRUE
    assert h.undecided_rounds == [4, 5]
    assert h.last_consensus_round == 3
    assert len(h.consensus_events()) == 20


def test_coin_false_world_stalls(coin_builder):
    """Coin forced to 0: the same tally never reaches a supermajority,
    w00 stays UNDEFINED forever and round 0 never decides — the
    hashgraph coin-round liveness hole, observable and deterministic."""
    b = coin_builder
    h, calls = _host_run(b, 0)
    assert len(calls) == 4  # diff=4 and diff=8 coin rounds both consulted
    r0 = h.store.get_round(0)
    assert r0.events[b.index["w00"]].famous == Trilean.UNDEFINED
    assert 0 in h.undecided_rounds
    assert h.last_consensus_round == 3  # rounds 1-3 decided regardless
    assert h.consensus_events() == []


@pytest.mark.parametrize("const", [0, 1], ids=["coin0", "coin1"])
def test_coin_world_device_parity(coin_builder, const):
    """The device kernel's coin tensor path (kernels.decide_fame
    coin_vote) must reproduce the host's coin-world outcome exactly —
    a sign flip in either engine breaks one of the two worlds."""
    b = coin_builder
    h, _ = _host_run(b, const)
    t = _device_run(b, const)
    w00 = b.index["w00"]
    assert (t.store.get_round(0).events[w00].famous
            == h.store.get_round(0).events[w00].famous)
    assert t.last_consensus_round == h.last_consensus_round
    assert t.consensus_events() == h.consensus_events()
    # full fame-table parity over every round
    for r in range(h.store.last_round() + 1):
        hr = h.store.get_round(r)
        tr = t.store.get_round(r)
        assert set(hr.witnesses()) == set(tr.witnesses()), f"round {r}"
        for w in hr.witnesses():
            assert hr.events[w].famous == tr.events[w].famous, (
                f"round {r} witness {w[:12]}")
