"""Epidemic broadcast tree (docs/gossip.md): the Plumtree-style
two-tier dissemination layer.

Covers the tree protocol end to end:

- wire forms: IHAVE/GRAFT/PRUNE dicts round-trip, the packed
  `ColumnarDigests` codec round-trips, and the EagerSync `Plum` marker
  follows the sidecar contract (absent => byte-identical legacy form);
- tree state machine: initial fan-out, GRAFT promotes / PRUNE demotes,
  the fan-out cap demotes the lowest-scoring edge, and a duplicate
  delivery never strips the last eager peer;
- live convergence: GRAFT/PRUNE drive the eager plane toward one
  delivery per event (eager-leg redundancy well under the pull
  baseline), with consensus byte-identical;
- repair: an asymmetric partition and a crashed eager parent both heal
  through the lazy plane (grafts fire, order stays byte-identical);
- interop: mixed plumtree/legacy-pull clusters commit byte-identical
  blocks, and --no_plumtree restores pull-only behavior;
- dedup-before-verify: a duplicate costs a hash lookup, not an ECDSA
  call — the verify-call counter tracks NEW events, not offered ones,
  under duplicate injection;
- bounds: IHAVE digests chunk under max_msg_bytes, GRAFT serves cut to
  the largest topological prefix that fits, and the new RPC kinds
  answer not-ready with request-matching response types.
"""

from __future__ import annotations

import queue
import time

from babble_tpu import crypto
from babble_tpu.hashgraph.inmem_store import InmemStore
from babble_tpu.net import FaultyTransport, InmemTransport
from babble_tpu.net.columnar import ColumnarDigests, wire_payload_nbytes
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.net.transport import (
    EagerSyncRequest,
    GraftRequest,
    GraftResponse,
    IHaveRequest,
    IHaveResponse,
    PruneRequest,
    PruneResponse,
    RPC,
)
from babble_tpu.node import Node
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.node.core import Core
from babble_tpu.node.state import NodeState
from babble_tpu.proxy import InmemAppProxy

from test_node import check_gossip, make_keyed_peers

CACHE = 10000


def _make_net(n=4, heartbeat=0.01, plumtree=True, eager_fanout=0,
              seed=11, faulty=False, graft_timeout=0.08,
              ihave_interval=0.05, **faults):
    """A localhost testnet with fast plumtree timers. `plumtree` may be
    a bool (all nodes) or a per-node list (mixed clusters); `faulty`
    wraps every transport in a (fault-free) FaultyTransport so tests
    can partition/crash mid-run."""
    inner = [InmemTransport(f"addr{i}", timeout=2.0) for i in range(n)]
    connect_all(inner)
    if faults or faulty:
        trans = {t.local_addr(): FaultyTransport(t, seed=seed, **faults)
                 for t in inner}
    else:
        trans = {t.local_addr(): t for t in inner}
    entries = make_keyed_peers(n, addr_fn=lambda i: f"addr{i}")
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    flags = plumtree if isinstance(plumtree, (list, tuple)) \
        else [plumtree] * n
    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = fast_config(heartbeat=heartbeat)
        conf.plumtree = flags[i]
        conf.eager_fanout = eager_fanout
        # Tight repair timers (default) so partition/crash tests
        # settle fast; convergence tests pass gentler ones — a graft
        # timeout below the contended delivery latency makes the lazy
        # plane race the eager one into promote/prune churn.
        conf.ihave_interval = ihave_interval
        conf.graft_timeout = graft_timeout
        conf.anti_entropy_interval = 0.3
        store = InmemStore(participants, CACHE)
        nodes.append(Node(conf, i, key, peers, store,
                          trans[peer.net_addr], InmemAppProxy()))
        nodes[-1].init()
    return nodes


def _run_until_round(nodes, target_round=3, timeout=60.0, live=None):
    live = nodes if live is None else live
    for nd in live:
        if nd.state.get_state() != NodeState.SHUTDOWN:
            nd.run_async(gossip=True)
    return _drive_until_round(nodes, target_round, timeout, live)


def _drive_until_round(nodes, target_round, timeout=60.0, live=None):
    live = nodes if live is None else live
    deadline = time.monotonic() + timeout
    i = 0
    while time.monotonic() < deadline:
        live[i % len(live)].submit_tx(b"ptx %d" % i)
        i += 1
        if all((nd.core.get_last_consensus_round_index() or 0)
               >= target_round for nd in live):
            return
        time.sleep(0.02)
    rounds = [nd.core.get_last_consensus_round_index() for nd in live]
    raise AssertionError(f"net never reached round {target_round}: "
                         f"{rounds}")


def _shutdown(nodes):
    for nd in nodes:
        nd.shutdown()


# ------------------------------------------------------------ wire forms


def test_rpc_wire_forms_round_trip():
    ih = IHaveRequest(3, [(0, 5, "0x" + "AB" * 32), (2, 7, "0x" + "CD" * 32)])
    assert IHaveRequest.from_dict(ih.to_dict()) == ih
    gr = GraftRequest(1, {0: 4, 1: -1, 2: 9})
    assert GraftRequest.from_dict(gr.to_dict()) == gr
    pr = PruneRequest(2)
    assert PruneRequest.from_dict(pr.to_dict()) == pr
    assert IHaveResponse.from_dict(IHaveResponse(1, False).to_dict()) \
        == IHaveResponse(1, False)
    assert PruneResponse.from_dict(PruneResponse(1).to_dict()) \
        == PruneResponse(1)
    gresp = GraftResponse(4, sync_limit=True)
    back = GraftResponse.from_dict(gresp.to_dict())
    assert back.sync_limit and back.from_id == 4 and back.events == []


def test_columnar_digest_codec_round_trips():
    digests = [(0, 5, "0x" + "AB" * 32), (2, 7, "0x" + "0F" * 32)]
    cols = ColumnarDigests.from_list(digests)
    assert len(cols) == 2
    assert cols.to_list() == digests
    decoded = ColumnarDigests.decode(cols.encode())
    assert decoded.to_list() == digests
    assert cols.nbytes() == len(cols.encode())
    # IHaveRequest downconverts a packed payload transparently
    req = IHaveRequest(1, cols)
    assert IHaveRequest.from_dict(req.to_dict()).digests == digests


def test_plum_marker_is_a_sidecar():
    """Absent marker => the legacy EagerSyncRequest dict is
    byte-identical (pinned like _TraceID/_CreateNs)."""
    plain = EagerSyncRequest(1, [])
    assert "Plum" not in plain.to_dict()
    marked = EagerSyncRequest(1, [], plum=True)
    d = marked.to_dict()
    assert d["Plum"] is True
    assert EagerSyncRequest.from_dict(d).plum is True
    assert EagerSyncRequest.from_dict(plain.to_dict()).plum is False


# ------------------------------------------------------ tree state machine


def test_tree_state_transitions_and_fanout_cap():
    nodes = _make_net(4, eager_fanout=2)
    try:
        pt = nodes[0].plumtree
        assert pt is not None
        eager0 = set(pt.eager_peers())
        assert len(eager0) == 2
        assert set(pt.eager_peers()) | set(pt.lazy_peers()) \
            == {"addr1", "addr2", "addr3"}

        lazy = pt.lazy_peers()[0]
        # Inbound GRAFT promotes, and the cap demotes someone else.
        pt.on_graft(lazy)
        assert lazy in pt.eager_peers()
        assert len(pt.eager_peers()) == 2
        # Inbound PRUNE demotes.
        victim = pt.eager_peers()[0]
        pt.on_prune(victim)
        assert victim not in pt.eager_peers()
        # A duplicate delivery never strips the LAST eager edge.
        last = pt.eager_peers()
        assert len(last) == 1
        pt.note_duplicate_push(last[0])
        assert pt.eager_peers() == last
        # Breaker suspension demotes and promotes a healthy lazy peer.
        pt.promote("addr1", reason="test")
        suspended = pt.eager_peers()[0]
        pt.on_peer_suspended(suspended)
        assert suspended not in pt.eager_peers()
    finally:
        _shutdown(nodes)


def test_kill_switch_restores_pull_only():
    nodes = _make_net(4, plumtree=False)
    try:
        assert all(nd.plumtree is None for nd in nodes)
        _run_until_round(nodes, target_round=3)
        for nd in nodes:
            legs = {leg for (_peer, leg) in nd._gossip_children}
            assert legs <= {"pull", "push_in"}, legs
            assert nd.get_gossip_stats()["plumtree"] == {"enabled": False}
            assert nd.plumtree_peer_roles() == {}
    finally:
        _shutdown(nodes)
    check_gossip(nodes)


# ------------------------------------------------------- live convergence


def test_live_net_converges_to_single_delivery():
    """GRAFT/PRUNE must converge the eager plane toward <= 1 delivery
    per event: in a settled window the eager-leg redundancy ratio sits
    far below the committed pull-only baseline (0.77-0.98 at n>=8;
    ~0.4+ even at n=3)."""
    nodes = _make_net(5, graft_timeout=0.5, ihave_interval=0.2)
    try:
        # Settle: early rounds carry the pre-prune redundancy the
        # windowed PRUNE trigger is busy converging away (measured
        # ~1.1 at round 6 -> 0.03 by round 12 on a 1-core runner).
        _run_until_round(nodes, target_round=8, timeout=90.0)

        def eager_counts():
            new = dup = 0
            for nd in nodes:
                for (_p, leg), ch in list(nd._gossip_children.items()):
                    if leg == "eager":
                        new += ch["new"].value
                        dup += ch["duplicate"].value
            return new, dup

        # Up to three 5-round windows: convergence is monotone in
        # expectation but 1-core scheduling can stretch one window —
        # the tree has converged when ANY settled window is far below
        # the committed pull baseline (0.77-0.98 at n>=8).
        target = (nodes[0].core.get_last_consensus_round_index() or 8)
        ratios = []
        for _ in range(3):
            n0, d0 = eager_counts()
            target += 5
            _drive_until_round(nodes, target_round=target, timeout=90.0)
            n1, d1 = eager_counts()
            new, dup = n1 - n0, d1 - d0
            assert new > 0, "no eager deliveries in the settle window"
            ratios.append(dup / new)
            if ratios[-1] < 0.6:
                break
        assert min(ratios) < 0.6, (
            f"eager redundancy {ratios} — the tree never converged "
            "(pull baseline: 0.77-0.98)")
        # The tree stayed within its fan-out caps.
        for nd in nodes:
            assert len(nd.plumtree.eager_peers()) <= nd.plumtree.fanout
    finally:
        _shutdown(nodes)
    check_gossip(nodes)


def test_mixed_plumtree_and_legacy_cluster_converges():
    """Half the cluster on the tree, half on reference pull-only:
    byte-identical blocks either way (the tree RPCs are sidecars the
    legacy nodes ack benignly, and the legacy pulls still drain the
    plumtree nodes' DAGs)."""
    nodes = _make_net(4, plumtree=[True, True, False, False])
    try:
        _run_until_round(nodes, target_round=5, timeout=90.0)
        assert nodes[0].plumtree is not None
        assert nodes[2].plumtree is None
    finally:
        _shutdown(nodes)
    check_gossip(nodes)


# --------------------------------------------------------------- repair


def test_partition_heal_tree_repairs():
    """An asymmetric partition around one node breaks its tree edges;
    the lazy plane (IHAVE -> GRAFT) and the breaker repair it, and
    after healing the whole net commits byte-identical blocks."""
    nodes = _make_net(4, seed=23, faulty=True)
    try:
        _run_until_round(nodes, target_round=2, timeout=60.0)
        # Cut node3 off from 0 and 1 in BOTH directions; 2 remains its
        # only path.
        for a, b in ((0, 3), (1, 3)):
            nodes[a].trans.partition(f"addr{b}")
            nodes[b].trans.partition(f"addr{a}")
        _drive_until_round(nodes, target_round=5, timeout=90.0)
        for a, b in ((0, 3), (1, 3)):
            nodes[a].trans.heal()
            nodes[b].trans.heal()
        _drive_until_round(nodes, target_round=7, timeout=90.0)
    finally:
        _shutdown(nodes)
    check_gossip(nodes)


def test_crashed_eager_parent_heals_through_lazy_plane():
    """Crash a node outright: peers that had it as an eager parent keep
    receiving events (grafted/AE through survivors), the breaker
    demotes the corpse from every eager set, and on restore it catches
    back up."""
    nodes = _make_net(4, seed=31, faulty=True)
    try:
        _run_until_round(nodes, target_round=2, timeout=60.0)
        nodes[1].trans.crash()
        live = [nodes[0], nodes[2], nodes[3]]
        _drive_until_round(live, target_round=6, timeout=90.0, live=live)

        # The corpse leaves every survivor's eager set (breaker
        # feedback). Poll: a breaker-repair promotion can transiently
        # re-try the corpse until its next three pushes fail.
        def corpse_evicted():
            return all(
                "addr1" not in nd.plumtree.eager_peers()
                or not nd.peer_healthy("addr1")
                for nd in live if nd.plumtree is not None)

        deadline = time.monotonic() + 20.0
        while not corpse_evicted() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert corpse_evicted()
        nodes[1].trans.restore()
        _drive_until_round(nodes, target_round=8, timeout=120.0,
                           live=live)
    finally:
        _shutdown(nodes)
    check_gossip([nodes[0], nodes[2], nodes[3]])


def test_missing_digest_grafts_from_announcer():
    """Deterministic lazy-repair loop: B learns via IHAVE that A has
    events it lacks; the graft timer fires, B pulls the gap from A and
    promotes the edge — no heartbeat gossip involved."""
    nodes = _make_net(2, eager_fanout=1)
    a, b = nodes
    try:
        a.run_async(gossip=False)  # serves RPCs only
        # Give A some history B lacks.
        for i in range(3):
            with a.core_lock:
                a.core.add_transactions([b"atx %d" % i])
                a.core.add_self_event()
        diff = a.core.diff(b.core.known())
        assert diff
        digests = [(ev.body.creator_id, ev.index(), ev.hex())
                   for ev in diff]
        pt = b.plumtree
        pt.on_ihave("addr0", digests)
        assert pt.snapshot()["missing_tracked"] == len(digests)
        # Fire the graft deadline by hand (worker not started).
        pt._check_missing(time.monotonic() + 10.0)
        kind, addr, _h = pt._control.get_nowait()
        assert (kind, addr) == ("graft", "addr0")
        pt._do_graft(addr)
        assert "addr0" in pt.eager_peers()
        for ev in diff:
            assert b.core.hg.store.has_event(ev.hex())
        # Arrival settles the missing tracker (past the re-armed
        # retry deadline of the first check).
        pt._check_missing(time.monotonic() + 60.0)
        assert pt.snapshot()["missing_tracked"] == 0
        # And A promoted B in return (GRAFT is symmetric).
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline \
                and "addr1" not in a.plumtree.eager_peers():
            time.sleep(0.01)
        assert "addr1" in a.plumtree.eager_peers()
    finally:
        _shutdown(nodes)


# ------------------------------------------------- dedup-before-verify


def test_dedup_before_verify_skips_duplicate_ecdsa():
    """A re-offered batch costs hash lookups, not ECDSA: the verify
    counter moves only for NEW events."""
    entries = make_keyed_peers(2, seed_base=7700)
    participants = {p.pub_key_hex: i for i, (_, p) in enumerate(entries)}
    cores = []
    for i, (key, _) in enumerate(entries):
        c = Core(i, key, participants, InmemStore(participants, CACHE))
        c.init()
        cores.append(c)
    a, b = cores
    diff = a.diff(b.known())
    payload = a.to_wire_batch(diff, "columnar")
    v0 = b._m_verified.value
    b.sync(payload)
    assert b._m_verified.value - v0 == len(diff)
    v1 = b._m_verified.value
    b.sync(a.to_wire_batch(diff, "columnar"))  # all duplicates
    assert b._m_verified.value == v1, "duplicates reached ECDSA"
    assert not b._verify_inflight  # in-flight set drained


def test_duplicate_injection_drops_verify_call_count():
    """Satellite gate: under at-least-once duplicate injection the
    ECDSA verify-call count tracks new events, NOT offered ones — the
    dedup check eats the duplicate share before libcrypto sees it."""
    nodes = _make_net(3, duplicate=1.0)
    try:
        _run_until_round(nodes, target_round=2)
    finally:
        _shutdown(nodes)
    offered = sum(nd._m_gossip_agg["offered"].value for nd in nodes)
    new = sum(nd._m_gossip_agg["new"].value for nd in nodes)
    stale = sum(nd._m_gossip_agg["stale"].value for nd in nodes)
    dup = sum(nd._m_gossip_agg["duplicate"].value for nd in nodes)
    verified = sum(nd.core._m_verified.value for nd in nodes)
    assert dup > 0, "the fault plan injected nothing"
    # Every verify was spent on a fresh event (small slack for batches
    # racing the unlocked verify window), and the duplicate share was
    # never verified at all.
    assert verified <= (new + stale) * 1.1 + 5, (
        f"verified={verified} new={new} stale={stale}")
    assert verified < offered, (
        f"verified={verified} offered={offered} — dedup saved nothing")


# ------------------------------------------------------------- bounds


def test_graft_serve_respects_max_msg_bytes():
    nodes = _make_net(2)
    a, b = nodes
    try:
        for i in range(40):
            with a.core_lock:
                a.core.add_transactions([b"bulk tx %d that pads" % i])
                a.core.add_self_event()
        full = a.core.diff({pid: -1 for pid in a.core.known()})
        # Tight cap: the serve must cut to a topological prefix.
        a.conf.max_msg_bytes = 2000
        rpc = RPC(GraftRequest(1, {pid: -1 for pid in a.core.known()}))
        a._process_graft_request(rpc, rpc.command)
        resp = rpc.resp_chan.get(timeout=2.0)
        assert resp.error is None
        events = resp.response.events
        served = events if isinstance(events, list) else \
            events.to_wire_events()
        assert 0 < len(served) < len(full)
        assert wire_payload_nbytes(resp.response.events) <= 2000
        # Prefix property: served events resolve on their own (B can
        # ingest them without the rest).
        with b.core_lock:
            b._sync(resp.response.events, "addr0", "graft")
    finally:
        _shutdown(nodes)


def test_ihave_digests_chunk_under_max_msg_bytes():
    nodes = _make_net(2, eager_fanout=1)
    try:
        pt = nodes[0].plumtree
        pt.max_msg_bytes = 1024  # ~10 digests per chunk at 96 B each
        jobs = []
        pt._submit_control = jobs.append
        # Make addr1 lazy FIRST (demoting resets its digest cursor),
        # then stage the announcements.
        pt.demote("addr1")
        digests = [(0, i, "0x" + ("%064X" % i)) for i in range(50)]
        with pt._lock:
            pt._digests.extend(digests)
        pt._announce()
        ihaves = [j for j in jobs if j[0] == "ihave"]
        assert len(ihaves) > 1, "oversized digest list never chunked"
        chunk_cap = max(1, (1024 - 64) // 96)
        for _kind, _addr, chunk in ihaves:
            assert len(chunk) <= chunk_cap
        assert sum(len(j[2]) for j in ihaves) == 50
    finally:
        _shutdown(nodes)


def test_not_ready_rpcs_answer_matching_types():
    nodes = _make_net(2)
    nd = nodes[0]
    try:
        nd.state.set_state(NodeState.CATCHING_UP)
        cases = [
            (IHaveRequest(1, []), IHaveResponse),
            (GraftRequest(1, {}), GraftResponse),
            (PruneRequest(1), PruneResponse),
        ]
        for cmd, resp_type in cases:
            rpc = RPC(cmd)
            nd._process_rpc(rpc)
            out = rpc.resp_chan.get(timeout=2.0)
            assert isinstance(out.response, resp_type), cmd
            assert out.error is not None
            assert "not ready" in str(out.error)
    finally:
        nd.state.set_state(NodeState.BABBLING)
        _shutdown(nodes)


def test_plumtree_debug_views():
    """The /debug surfaces: gossip stats carry the tree section and
    peer roles join /debug/peers-style views."""
    nodes = _make_net(3)
    try:
        _run_until_round(nodes, target_round=2)
        nd = nodes[0]
        snap = nd.get_gossip_stats()["plumtree"]
        assert snap["fanout"] >= 1
        assert set(snap["eager"]) | set(snap["lazy"]) \
            == {"addr1", "addr2"}
        roles = nd.plumtree_peer_roles()
        assert set(roles.values()) <= {"eager", "lazy"}
        assert set(roles) == {"addr1", "addr2"}
    finally:
        _shutdown(nodes)
