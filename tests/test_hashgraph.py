"""Consensus-engine tests ported from the reference's algorithmic suite
(reference hashgraph/hashgraph_test.go). These fixtures and assertions are
the parity oracle for both engines (host + TPU)."""

import pytest

from babble_tpu.gojson import Timestamp
from babble_tpu.hashgraph import Event, InmemStore, Root, Trilean
from babble_tpu.hashgraph.graph import MAX_INT32, InsertError

from fixtures import (
    GraphBuilder,
    Play,
    build_basic_graph,
    build_consensus_graph,
    build_funky_graph,
    build_round_graph,
)


# ---------------------------------------------------------------- ancestry


def test_ancestor():
    h, b = build_basic_graph()
    i = b.index
    # 1 generation
    for x, y in [("e01", "e0"), ("e01", "e1"), ("s00", "e01"), ("s20", "e2"),
                 ("e20", "s00"), ("e20", "s20"), ("e12", "e20"), ("e12", "s10")]:
        assert h.ancestor(i[x], i[y]), f"{y} should be ancestor of {x}"
    # 2 generations
    for x, y in [("s00", "e0"), ("s00", "e1"), ("e20", "e01"), ("e20", "e2"),
                 ("e12", "e1"), ("e12", "s20")]:
        assert h.ancestor(i[x], i[y])
    # 3 generations
    for x, y in [("e20", "e0"), ("e20", "e1"), ("e20", "e2"), ("e12", "e01"),
                 ("e12", "e0"), ("e12", "e1"), ("e12", "e2")]:
        assert h.ancestor(i[x], i[y])
    # false positives
    assert not h.ancestor(i["e01"], i["e2"])
    assert not h.ancestor(i["s00"], i["e2"])
    assert not h.ancestor(i["e0"], "")
    assert not h.ancestor(i["s00"], "")
    assert not h.ancestor(i["e12"], "")


def test_self_ancestor():
    h, b = build_basic_graph()
    i = b.index
    assert h.self_ancestor(i["e01"], i["e0"])
    assert h.self_ancestor(i["s00"], i["e01"])
    assert not h.self_ancestor(i["e01"], i["e1"])
    assert not h.self_ancestor(i["e12"], i["e20"])
    assert not h.self_ancestor(i["s20"], "")
    assert h.self_ancestor(i["e20"], i["e2"])
    assert h.self_ancestor(i["e12"], i["e1"])
    assert not h.self_ancestor(i["e20"], i["e0"])
    assert not h.self_ancestor(i["e12"], i["e2"])
    assert not h.self_ancestor(i["e20"], i["e01"])


def test_see():
    h, b = build_basic_graph()
    i = b.index
    for x, y in [("e01", "e0"), ("e01", "e1"), ("e20", "e0"), ("e20", "e01"),
                 ("e12", "e01"), ("e12", "e0"), ("e12", "e1"), ("e12", "s20")]:
        assert h.see(i[x], i[y]), f"{x} should see {y}"


# ---------------------------------------------------------------- forks


def test_fork_rejected():
    """Reference hashgraph_test.go:299-363: a second index-0 event by the
    same creator must be rejected, as must descendants referencing it."""
    b = GraphBuilder(3)
    h = b.make_hashgraph()

    for i in range(3):
        ev = b.add_initial(f"e{i}", i)
        h.insert_event(ev, True)

    # fork: node 2 creates another index-0 event with a different payload
    node2 = b.nodes[2]
    fork = Event.new([b"yo"], ["", ""], node2.pub, 0, timestamp=b._next_ts())
    fork.sign(node2.key)
    b.index["a"] = fork.hex()
    with pytest.raises(InsertError):
        h.insert_event(fork, True)

    e01 = Event.new([], [b.index["e0"], b.index["a"]], b.nodes[0].pub, 1,
                    timestamp=b._next_ts())
    e01.sign(b.nodes[0].key)
    b.index["e01"] = e01.hex()
    with pytest.raises(InsertError):
        h.insert_event(e01, True)

    e20 = Event.new([], [b.index["e2"], b.index["e01"]], node2.pub, 1,
                    timestamp=b._next_ts())
    e20.sign(node2.key)
    with pytest.raises(InsertError):
        h.insert_event(e20, True)


# ---------------------------------------------------------------- insert


def test_insert_event_coordinates_and_wire():
    h, b = build_round_graph()
    i = b.index
    participants = h.participants

    e0 = h.store.get_event(i["e0"])
    assert e0.body.self_parent_index == -1
    assert e0.body.other_parent_creator_id == -1
    assert e0.body.other_parent_index == -1
    assert e0.body.creator_id == participants[e0.creator()]

    assert [(c.index, c.hash) for c in e0.first_descendants] == [
        (0, i["e0"]), (1, i["e10"]), (2, i["e21"])]
    assert [c.index for c in e0.last_ancestors] == [0, -1, -1]
    assert e0.last_ancestors[0].hash == i["e0"]

    e21 = h.store.get_event(i["e21"])
    e10 = h.store.get_event(i["e10"])
    assert e21.body.self_parent_index == 1
    assert e21.body.other_parent_creator_id == participants[e10.creator()]
    assert e21.body.other_parent_index == 1
    assert e21.body.creator_id == participants[e21.creator()]
    assert [(c.index, c.hash) for c in e21.first_descendants] == [
        (2, i["e02"]), (3, i["f1"]), (2, i["e21"])]
    assert [(c.index, c.hash) for c in e21.last_ancestors] == [
        (0, i["e0"]), (1, i["e10"]), (2, i["e21"])]

    f1 = h.store.get_event(i["f1"])
    assert f1.body.self_parent_index == 2
    assert f1.body.other_parent_creator_id == participants[e0.creator()]
    assert f1.body.other_parent_index == 2
    assert f1.body.creator_id == participants[f1.creator()]
    assert f1.first_descendants[0].index == MAX_INT32
    assert (f1.first_descendants[1].index, f1.first_descendants[1].hash) == (3, i["f1"])
    assert f1.first_descendants[2].index == MAX_INT32
    assert [(c.index, c.hash) for c in f1.last_ancestors] == [
        (2, i["e02"]), (3, i["f1"]), (2, i["e21"])]

    assert h.pending_loaded_events == 4


def test_read_wire_info_roundtrip():
    h, b = build_round_graph()
    for name, evh in b.index.items():
        ev = h.store.get_event(evh)
        wire = ev.to_wire()
        ev2 = h.read_wire_info(wire)
        assert ev2.body.parents == ev.body.parents, name
        assert ev2.body.creator == ev.body.creator, name
        assert ev2.body.index == ev.body.index, name
        assert ev2.body.timestamp == ev.body.timestamp, name
        assert (ev2.body.transactions or []) == (ev.body.transactions or []), name
        assert (ev2.r, ev2.s) == (ev.r, ev.s), name
        assert ev2.hex() == ev.hex(), name
        assert ev2.verify(), name


# ---------------------------------------------------------------- strongly see


def test_strongly_see():
    h, b = build_round_graph()
    i = b.index
    for x, y in [("e21", "e0"), ("e02", "e10"), ("e02", "e0"), ("e02", "e1"),
                 ("f1", "e21"), ("f1", "e10"), ("f1", "e0"), ("f1", "e1"),
                 ("f1", "e2"), ("s11", "e2")]:
        assert h.strongly_see(i[x], i[y]), f"{x} should strongly see {y}"
    for x, y in [("e10", "e0"), ("e21", "e1"), ("e21", "e2"), ("e02", "e2"),
                 ("s11", "e02")]:
        assert not h.strongly_see(i[x], i[y]), f"{x} should not strongly see {y}"


# ---------------------------------------------------------------- rounds


def _seed_round_info(h, b):
    from babble_tpu.hashgraph import RoundInfo

    r0 = RoundInfo()
    for name in ["e0", "e1", "e2"]:
        r0.add_event(b.index[name], witness=True)
    h.store.set_round(0, r0)
    r1 = RoundInfo()
    r1.add_event(b.index["f1"], witness=True)
    h.store.set_round(1, r1)


def test_parent_round():
    h, b = build_round_graph()
    _seed_round_info(h, b)
    i = b.index
    assert h.parent_round(i["e0"]).round == -1
    assert h.parent_round(i["e0"]).is_root
    assert h.parent_round(i["e1"]).round == -1
    assert h.parent_round(i["e1"]).is_root
    assert h.parent_round(i["f1"]).round == 0
    assert not h.parent_round(i["f1"]).is_root
    assert h.parent_round(i["s11"]).round == 1
    assert not h.parent_round(i["s11"]).is_root


def test_witness():
    h, b = build_round_graph()
    _seed_round_info(h, b)
    i = b.index
    for w in ["e0", "e1", "e2", "f1"]:
        assert h.witness(i[w]), f"{w} should be witness"
    for w in ["e10", "e21", "e02"]:
        assert not h.witness(i[w]), f"{w} should not be witness"


def test_round_inc():
    h, b = build_round_graph()
    from babble_tpu.hashgraph import RoundInfo

    r0 = RoundInfo()
    for name in ["e0", "e1", "e2"]:
        r0.add_event(b.index[name], witness=True)
    h.store.set_round(0, r0)

    assert h.round_inc(b.index["f1"])
    assert not h.round_inc(b.index["e02"])


def test_round():
    h, b = build_round_graph()
    from babble_tpu.hashgraph import RoundInfo

    r0 = RoundInfo()
    for name in ["e0", "e1", "e2"]:
        r0.add_event(b.index[name], witness=True)
    h.store.set_round(0, r0)

    assert h.round(b.index["f1"]) == 1
    assert h.round(b.index["e02"]) == 0
    assert h.round_diff(b.index["f1"], b.index["e02"]) == 1
    assert h.round_diff(b.index["e02"], b.index["f1"]) == -1
    assert h.round_diff(b.index["e02"], b.index["e21"]) == 0


def test_divide_rounds():
    h, b = build_round_graph()
    h.divide_rounds()
    i = b.index

    assert h.store.last_round() == 1
    round0 = h.store.get_round(0)
    assert len(round0.witnesses()) == 3
    for w in ["e0", "e1", "e2"]:
        assert i[w] in round0.witnesses()
    round1 = h.store.get_round(1)
    assert round1.witnesses() == [i["f1"]]


# ---------------------------------------------------------------- consensus


def test_decide_fame():
    h, b = build_consensus_graph()
    i = b.index
    h.divide_rounds()
    h.decide_fame()

    assert h.round(i["g0"]) == 2
    assert h.round(i["g1"]) == 2
    assert h.round(i["g2"]) == 2

    round0 = h.store.get_round(0)
    for w in ["e0", "e1", "e2"]:
        ev = round0.events[i[w]]
        assert ev.witness and ev.famous == Trilean.TRUE, f"{w} should be famous"


def test_oldest_self_ancestor_to_see():
    h, b = build_consensus_graph()
    i = b.index
    assert h.oldest_self_ancestor_to_see(i["f0"], i["e1"]) == i["e02"]
    assert h.oldest_self_ancestor_to_see(i["f1"], i["e0"]) == i["e10"]
    assert h.oldest_self_ancestor_to_see(i["f1b"], i["e0"]) == i["e10"]
    assert h.oldest_self_ancestor_to_see(i["g2"], i["f1"]) == i["f2"]
    assert h.oldest_self_ancestor_to_see(i["e21"], i["e1"]) == i["e21"]
    assert h.oldest_self_ancestor_to_see(i["e2"], i["e1"]) == ""


def test_decide_round_received():
    h, b = build_consensus_graph()
    h.divide_rounds()
    h.decide_fame()
    h.decide_round_received()
    for name, hash_ in b.index.items():
        if name.startswith("e"):
            e = h.store.get_event(hash_)
            assert e.round_received == 1, f"{name} round received should be 1"


def test_find_order():
    h, b = build_consensus_graph()
    h.divide_rounds()
    h.decide_fame()
    h.find_order()

    consensus = h.consensus_events()
    assert len(consensus) == 7
    assert h.pending_loaded_events == 2
    assert b.get_name(consensus[0]) == "e0"
    assert b.get_name(consensus[6]) == "e02"


def test_blocks():
    h, _ = build_consensus_graph()
    h.divide_rounds()
    h.decide_fame()
    h.find_order()

    block0 = h.store.get_block(1)
    assert block0.round_received == 1
    assert block0.transactions == [b"e21"]


def test_known():
    h, _ = build_consensus_graph()
    assert h.known() == {0: 8, 1: 7, 2: 7}


# ---------------------------------------------------------------- reset/frames


def test_reset():
    h, b = build_consensus_graph()
    i = b.index
    evs = ["g1", "g0", "g2", "g10", "g21", "o02", "g02", "h1", "h0", "h2"]

    backup = {}
    for name in evs:
        ev = h.store.get_event(i[name])
        backup[name] = Event(ev.body, r=ev.r, s=ev.s)

    roots = {
        h.reverse_participants[0]: Root(
            x=i["f02b"], y=i["g1"], index=4, round=2,
            others={i["o02"]: i["f21"]},
        ),
        h.reverse_participants[1]: Root(x=i["f10"], y=i["f02b"], index=4, round=2),
        h.reverse_participants[2]: Root(x=i["f21"], y=i["g1"], index=4, round=2),
    }

    h.reset(roots)
    for name in evs:
        h.insert_event(backup[name], False)
        h.store.get_event(i[name])

    assert h.known() == {0: 8, 1: 7, 2: 7}


def test_get_frame():
    h, b = build_consensus_graph()
    i = b.index
    h.divide_rounds()
    h.decide_fame()
    h.find_order()

    expected_roots = {
        h.reverse_participants[0]: Root(x=i["e02"], y=i["f1b"], index=1, round=0),
        h.reverse_participants[1]: Root(x=i["e10"], y=i["e02"], index=1, round=0),
        h.reverse_participants[2]: Root(x=i["e21b"], y=i["f1b"], index=2, round=0),
    }

    frame = h.get_frame()
    for p, r in frame.roots.items():
        er = expected_roots[p]
        assert (r.x, r.y, r.index, r.round) == (er.x, er.y, er.index, er.round), p
        assert r.others == er.others, p

    skip = {
        h.reverse_participants[0]: 1,
        h.reverse_participants[1]: 1,
        h.reverse_participants[2]: 2,
    }
    expected_events = []
    for p in frame.roots:
        for e in h.store.participant_events(p, skip[p]):
            expected_events.append(h.store.get_event(e))
    expected_events.sort(key=lambda e: e.topological_index)
    assert [e.hex() for e in frame.events] == [e.hex() for e in expected_events]


def test_reset_from_frame():
    h, _ = build_consensus_graph()
    h.divide_rounds()
    h.decide_fame()
    h.find_order()

    frame = h.get_frame()
    h.reset(frame.roots)
    for ev in frame.events:
        h.insert_event(ev, False)

    assert h.known() == {0: 8, 1: 7, 2: 7}

    h.divide_rounds()
    h.decide_fame()
    h.find_order()
    assert h.last_consensus_round == 1


# ---------------------------------------------------------------- funky


def test_funky_fame():
    h, b = build_funky_graph()
    h.divide_rounds()
    assert h.store.last_round() == 5
    h.decide_fame()
    # rounds 0-3 decided; 4 (the coin round) and 5 remain
    assert h.undecided_rounds == [4, 5]


def test_funky_blocks():
    h, _ = build_funky_graph()
    h.divide_rounds()
    h.decide_fame()
    h.find_order()
    expected = {1: 6, 2: 7, 3: 7}
    for rr, n_txs in expected.items():
        b = h.store.get_block(rr)
        assert len(b.transactions) == n_txs, f"block {rr}"
