"""HTTP /Stats service — reference service/service.go: live JSON stats
with CORS from a running node."""

from __future__ import annotations

import json
import urllib.request

from babble_tpu.service import Service

from test_node import check_gossip, make_nodes, run_gossip


def test_stats_endpoint():
    nodes = make_nodes(4, "inmem")
    service = Service("127.0.0.1:0", nodes[0])
    service.serve_async()
    try:
        run_gossip(nodes, target_round=3)
        with urllib.request.urlopen(f"http://{service.addr}/Stats", timeout=2) as r:
            assert r.status == 200
            assert r.headers["Access-Control-Allow-Origin"] == "*"
            stats = json.loads(r.read())
        assert int(stats["last_consensus_round"]) >= 3
        assert stats["id"] == "0" or stats["id"].isdigit()
        assert float(stats["events_per_second"]) > 0
        check_gossip(nodes)

        # fault-tolerance stats surfaced over HTTP (docs/robustness.md)
        assert stats["engine_state"] == "host"
        assert stats["engine_failovers"] == "0"
        with urllib.request.urlopen(
            f"http://{service.addr}/debug/peers", timeout=2
        ) as r:
            assert r.status == 200
            dbg = json.loads(r.read())
        assert dbg["engine_state"] == "host"
        assert dbg["engine_failovers"] == 0
        assert len(dbg["peers"]) == 3  # 4-node net, self excluded
        for state in dbg["peers"].values():
            assert state["state"] in ("closed", "open", "half_open")
            assert {"failures", "successes", "trips",
                    "retry_in"} <= set(state)

        # per-phase timers now cover the host ingest stages too
        # (docs/ingest.md): from_wire / verify / insert ride under the
        # sync wall in /debug/phases.
        with urllib.request.urlopen(
            f"http://{service.addr}/debug/phases", timeout=2
        ) as r:
            assert r.status == 200
            ph = json.loads(r.read())["phases"]
        for stage in ("sync", "from_wire", "verify", "insert"):
            assert stage in ph, stage
            assert ph[stage]["calls"] >= 1
            assert ph[stage]["total_ns"] >= 0

        # live device profiling (reference mounts pprof on the same mux,
        # cmd/babble/main.go:12)
        with urllib.request.urlopen(
            f"http://{service.addr}/debug/profile?seconds=0.2", timeout=30
        ) as r:
            assert r.status == 200
            info = json.loads(r.read())
        assert "trace_dir" in info
        import os

        assert os.path.isdir(info["trace_dir"])
        try:
            urllib.request.urlopen(
                f"http://{service.addr}/debug/profile?seconds=nope",
                timeout=5)
            raise AssertionError("bad seconds accepted")
        except urllib.error.HTTPError as err:
            assert err.code == 400

        # durability view (docs/robustness.md "Crash recovery"): the
        # inmem store reports its type + in-memory delivered anchor
        assert stats["store_type"] == "inmem"
        assert "last_committed_block" in stats

        # POST /submit: transaction intake without a socket client
        # (crash-harness mode). The tx must reach consensus.
        req = urllib.request.Request(
            f"http://{service.addr}/submit",
            data=b"service submitted tx", method="POST")
        with urllib.request.urlopen(req, timeout=2) as r:
            assert r.status == 200
            assert json.loads(r.read())["submitted"] == len(
                b"service submitted tx")
        try:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://{service.addr}/submit", data=b"",
                    method="POST"), timeout=2)
            raise AssertionError("empty tx accepted")
        except urllib.error.HTTPError as err:
            assert err.code == 400
        # the unauthenticated intake caps the body it will buffer
        try:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://{service.addr}/submit",
                    data=b"x" * ((1 << 20) + 1), method="POST"),
                timeout=5)
            raise AssertionError("oversized tx accepted")
        except urllib.error.HTTPError as err:
            assert err.code == 413
    finally:
        service.close()
