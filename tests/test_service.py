"""HTTP /Stats service — reference service/service.go: live JSON stats
with CORS from a running node."""

from __future__ import annotations

import json
import urllib.request

from babble_tpu.service import Service

from test_node import check_gossip, make_nodes, run_gossip


def test_stats_endpoint():
    nodes = make_nodes(4, "inmem")
    service = Service("127.0.0.1:0", nodes[0])
    service.serve_async()
    try:
        run_gossip(nodes, target_round=3)
        with urllib.request.urlopen(f"http://{service.addr}/Stats", timeout=2) as r:
            assert r.status == 200
            assert r.headers["Access-Control-Allow-Origin"] == "*"
            stats = json.loads(r.read())
        assert int(stats["last_consensus_round"]) >= 3
        assert stats["id"] == "0" or stats["id"].isdigit()
        assert float(stats["events_per_second"]) > 0
        check_gossip(nodes)
    finally:
        service.close()
