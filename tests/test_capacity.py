"""Capacity observatory tests (docs/observability.md "Capacity"):
sizer units, the growth-slope fit, cache-efficiency carries, the
cardinality lint, and the live acceptance — a real 3-node net must
serve every capacity family over /metrics plus the ranked
/debug/capacity surface, a --no_capacity net must serve none of it,
and a FileStore frame reset must shrink the accounted state."""

from __future__ import annotations

import io
import json
import time
import urllib.request

import pytest

from babble_tpu import crypto
from babble_tpu.common.lru import LRU
from babble_tpu.common.rolling_index import RollingIndex
from babble_tpu.gojson import Timestamp
from babble_tpu.hashgraph import FileStore, InmemStore
from babble_tpu.hashgraph.event import MEMO_STATS, Event
from babble_tpu.hashgraph.root import Root
from babble_tpu.net import InmemTransport
from babble_tpu.net.inmem_transport import connect_all
from babble_tpu.node import Node
from babble_tpu.node.config import test_config as fast_config
from babble_tpu.proxy import InmemAppProxy
from babble_tpu.service import Service
from babble_tpu.telemetry import Registry, promtext
from babble_tpu.telemetry.capacity import (EVENT_BASE_BYTES,
                                           GrowthTracker, bytes_bytes,
                                           event_bytes, gc_snapshot,
                                           mem_budget_bytes,
                                           process_memory, sampled_bytes,
                                           series_counts, str_bytes)

from test_node import CACHE, make_keyed_peers, make_nodes, run_gossip
from test_store import make_participants, signed_event

CAPACITY_FAMILIES = [
    "babble_mem_bytes",
    'babble_mem_bytes{component="store_event_log"}',
    'babble_mem_bytes{component="consensus_memos"}',
    "babble_process_rss_bytes",
    "babble_process_rss_peak_bytes",
    "babble_mem_budget_bytes",
    "babble_gc_tracked_objects",
    "babble_gc_collections",
    "babble_shm_bytes",
    'babble_cache_hits_total{cache="store_events"}',
    'babble_cache_misses_total{cache="store_events"}',
    'babble_cache_hits_total{cache="pub_key"}',
    "babble_telemetry_series",
    "babble_telemetry_series_total",
]


# ---------------------------------------------------------------- sizers


def test_process_memory_and_budget():
    pm = process_memory()
    assert pm["rss_bytes"] > 0
    assert pm["rss_peak_bytes"] >= pm["rss_bytes"] * 0  # present
    assert mem_budget_bytes() > 0  # cgroup limit or MemTotal
    snap = gc_snapshot()
    assert len(snap["gen_counts"]) == 3


def test_string_and_bytes_sizers():
    assert str_bytes(None) == 0
    assert str_bytes("") == 0
    assert str_bytes("abcd") == 49 + 4
    assert bytes_bytes(None) == 0
    assert bytes_bytes(b"abcd") == 33 + 4


def test_event_bytes_counts_payload_and_memos():
    keys, pubs, _parts = make_participants(2)
    ev = signed_event(keys[0], pubs[0], ["", ""], 0, 10**18)
    base = event_bytes(ev)
    assert base >= EVENT_BASE_BYTES
    # Materializing the memoized encodings grows the estimate: the
    # sizer bills retained state, not just the object graph.
    ev.marshal()
    ev.hash()
    assert event_bytes(ev) > base
    # Never raises, even on junk.
    assert event_bytes(object()) == EVENT_BASE_BYTES


def test_sampled_bytes_exact_and_scaled():
    vals = [b"x" * 10] * 8
    exact = sampled_bytes(vals, 8, len, sample=256)
    assert exact == 80
    # Above the sample bound the estimate scales from the sampled
    # prefix — exact here because entries are uniform.
    scaled = sampled_bytes(iter([b"x" * 10] * 1000), 1000, len, sample=4)
    assert scaled == 10_000
    assert sampled_bytes([], 0, len) == 0


# ----------------------------------------------------------- growth model


def test_growth_tracker_slope_exact_on_linear_series():
    g = GrowthTracker(window=16)
    for x in range(10):
        g.observe("wal", x, 100.0 * x + 5.0)
    assert g.slope("wal") == pytest.approx(100.0)
    assert g.last("wal") == pytest.approx(905.0)
    # bytes to budget at the fitted slope
    assert g.to_budget("wal", 10_905.0) == pytest.approx(100.0)


def test_growth_tracker_dedups_same_x_and_bounds_series():
    g = GrowthTracker(window=4, max_series=2)
    g.observe("a", 1, 10)
    g.observe("a", 1, 20)  # same commit tick: keep freshest
    assert g.last("a") == 20
    assert g.slope("a") is None  # one distinct x
    g.observe("b", 1, 1)
    g.observe("c", 1, 1)  # over max_series: dropped
    assert sorted(g.series()) == ["a", "b"]
    for x in range(2, 20):
        g.observe("a", x, x)
    assert len(g._series["a"]) == 4  # windowed


def test_growth_tracker_flat_and_shrinking():
    g = GrowthTracker()
    for x in range(5):
        g.observe("flat", x, 7.0)
        g.observe("down", x, -3.0 * x)
    assert g.slope("flat") == pytest.approx(0.0)
    assert g.slope("down") == pytest.approx(-3.0)
    assert g.to_budget("flat", 100.0) is None  # not growing
    assert g.to_budget("down", 100.0) is None


# ------------------------------------------------------- efficiency carries


def test_lru_hit_miss_eviction_counters():
    lru = LRU(2)
    lru.add("a", 1)
    lru.add("b", 2)
    assert lru.get("a") == (1, True)
    assert lru.get("zz") == (None, False)
    lru.add("c", 3)  # evicts b
    assert (lru.hits, lru.misses, lru.evictions) == (1, 1, 1)
    # update-in-place is not an eviction
    lru.add("c", 4)
    assert lru.evictions == 1


def test_rolling_index_eviction_counter():
    ri = RollingIndex(2)  # capacity 4, rolls by dropping oldest 2
    for i in range(4):
        ri.add(f"e{i}", i)
    assert ri.evicted == 0
    ri.add("e4", 4)
    assert ri.evicted == 2


def test_event_memo_stats_count_marshal_and_hash_reuse():
    keys, pubs, _parts = make_participants(2)
    ev = signed_event(keys[0], pubs[0], ["", ""], 0, 10**18)
    before = MEMO_STATS.snapshot()
    ev.marshal()
    ev.marshal()
    ev.hash()
    ev.hash()
    after = MEMO_STATS.snapshot()
    assert after["marshal_misses"] - before["marshal_misses"] >= 1
    assert after["marshal_hits"] - before["marshal_hits"] >= 1
    assert after["hash_misses"] - before["hash_misses"] >= 1
    assert after["hash_hits"] - before["hash_hits"] >= 1


# ------------------------------------------------------- cardinality audit


def test_series_counts_across_registries():
    r1, r2 = Registry(), Registry()
    r1.gauge("babble_x", "x", node="0").set(1)
    r1.gauge("babble_x", "x", node="1").set(1)
    r2.gauge("babble_x", "x", node="2").set(1)
    r2.counter("babble_y", "y").inc()
    counts = series_counts(r1, r2)
    assert counts["babble_x"] == 3
    assert counts["babble_y"] == 1


def test_promtext_family_series_counts_folds_histograms():
    text = "\n".join([
        'babble_g{node="0"} 1',
        'babble_g{node="1"} 2',
        'babble_h_bucket{node="0",le="0.1"} 1',
        'babble_h_bucket{node="0",le="+Inf"} 2',
        'babble_h_sum{node="0"} 0.3',
        'babble_h_count{node="0"} 2',
    ])
    samples, _ = promtext.parse(text)
    counts = promtext.family_series_counts(samples)
    # two gauge children; ONE histogram child (le stripped, the
    # _bucket/_sum/_count sample names fold onto the family)
    assert counts["babble_g"] == 2
    assert counts["babble_h"] == 1


def test_promtext_max_series_lint(monkeypatch, capsys):
    text = "\n".join(f'babble_fat{{peer="{i}"}} 1' for i in range(5))
    monkeypatch.setattr("sys.stdin", io.StringIO(text))
    assert promtext.main(["--max-series", "4"]) == 1
    assert "babble_fat" in capsys.readouterr().err
    monkeypatch.setattr("sys.stdin", io.StringIO(text))
    assert promtext.main(["--max-series", "5"]) == 0


# --------------------------------------------------------- store accounting


def _fill_store(store, keys, pubs, n_events=40):
    heads = {p: "" for p in pubs}
    seqs = {p: -1 for p in pubs}
    ts = 10**18
    for i in range(n_events):
        p = pubs[i % len(pubs)]
        seqs[p] += 1
        ts += 1
        ev = signed_event(keys[i % len(pubs)], p,
                          [heads[p], ""], seqs[p], ts)
        store.set_event(ev)
        heads[p] = ev.hex()


def test_inmem_store_capacity_stats_accounts_events():
    keys, pubs, participants = make_participants(2)
    store = InmemStore(participants, 100)
    empty = store.capacity_stats()
    assert empty["components"]["store_event_log"]["rows"] == 0
    _fill_store(store, keys, pubs)
    stats = store.capacity_stats()
    log = stats["components"]["store_event_log"]
    assert log["rows"] == 40
    assert log["bytes"] > 40 * EVENT_BASE_BYTES
    assert stats["caches"]["store_events"]["misses"] >= 0


def test_file_store_capacity_shrinks_after_reset(tmp_path):
    keys, pubs, participants = make_participants(2)
    fs = FileStore(participants, 100, str(tmp_path / "cap.db"))
    fs.begin_batch()
    _fill_store(fs, keys, pubs)
    fs.commit_batch()
    before = fs.capacity_stats()
    assert before["components"]["store_event_log"]["rows"] == 40
    assert before["files"]["db"] > 0
    db_before = before["files"]["db"]
    # Frame reset drops pre-reset history (db + hot cache): the
    # accounted state must shrink with it — the one shrink path the
    # growth model should ever see from the store.
    fs.reset({p: Root() for p in pubs})
    after = fs.capacity_stats()
    assert after["components"]["store_event_log"]["rows"] == 0
    assert after["components"]["store_event_log"]["bytes"] < \
        before["components"]["store_event_log"]["bytes"]
    assert after["files"]["db"] <= db_before
    fs.close()


# ------------------------------------------------------- live acceptance


def _scrape(svc):
    with urllib.request.urlopen(
            f"http://{svc.addr}/metrics", timeout=10) as r:
        return promtext.parse(r.read().decode())


def test_live_capacity_scrape_and_debug_surface():
    """A live 3-node net serves every capacity family over /metrics,
    and /debug/capacity returns the assembled snapshot: components,
    cache efficiency (including the process-wide pub-key LRU and
    event memos), growth slopes, and the ranked top-growers table."""
    nodes = make_nodes(3, "inmem")
    svc = None
    try:
        svc = Service("127.0.0.1:0", nodes[0])
        svc.serve_async()
        run_gossip(nodes, target_round=2, shutdown=False)
        samples, _ = _scrape(svc)
        missing = promtext.check_series(samples, CAPACITY_FAMILIES)
        assert not missing, missing
        # every exported component byte gauge is non-negative
        for lb, v in samples["babble_mem_bytes"]:
            assert v >= 0, lb
        # the live scrape passes the cardinality lint: no family fans
        # out past a sane per-family ceiling (a per-event or
        # per-digest label would blow straight through this)
        fat = {f: c for f, c in
               promtext.family_series_counts(samples).items() if c > 200}
        assert not fat, fat
        with urllib.request.urlopen(
                f"http://{svc.addr}/debug/capacity", timeout=10) as r:
            cap = json.loads(r.read())
        assert cap["enabled"] is True
        assert cap["components"]["store_event_log"]["rows"] > 0
        assert cap["process"]["rss_bytes"] > 0
        assert "pub_key" in cap["caches"]
        assert "event_marshal" in cap["caches"]
        assert cap["caches"]["store_events"]["hits"] >= 0
        assert cap["series"]["total"] > 0
        assert isinstance(cap["top_growers"], list)
        # a second read a beat later grows the slope window
        time.sleep(0.2)
        with urllib.request.urlopen(
                f"http://{svc.addr}/debug/capacity", timeout=10) as r:
            cap2 = json.loads(r.read())
        assert cap2["committed_block"] >= cap["committed_block"]
    finally:
        if svc is not None:
            svc.close()
        for nd in nodes:
            nd.shutdown()


def _build_net_no_capacity(n=3):
    transports = [InmemTransport(f"addr{i}", timeout=2.0)
                  for i in range(n)]
    connect_all(transports)
    entries = make_keyed_peers(n, addr_fn=lambda i: f"addr{i}")
    by_addr = {t.local_addr(): t for t in transports}
    peers = [p for _, p in entries]
    participants = {p.pub_key_hex: i for i, p in enumerate(peers)}
    nodes = []
    for i, (key, peer) in enumerate(entries):
        conf = fast_config(heartbeat=0.01)
        conf.capacity = False
        store = InmemStore(participants, CACHE)
        node = Node(conf, i, key, peers, store,
                    by_addr[peer.net_addr], InmemAppProxy())
        node.init()
        nodes.append(node)
    return nodes


def test_no_capacity_kill_switch_exports_nothing():
    """--no_capacity parity: the scrape carries no capacity families
    from this node and /debug/capacity answers {"enabled": false} —
    the whole plane is a strict no-op."""
    nodes = _build_net_no_capacity()
    svc = None
    try:
        svc = Service("127.0.0.1:0", nodes[0])
        svc.serve_async()
        run_gossip(nodes, target_round=2, shutdown=False)
        samples, _ = _scrape(svc)
        node_label = str(nodes[0].id)
        for fam in ("babble_mem_bytes", "babble_growth_bytes_per_block",
                    "babble_telemetry_series", "babble_store_bytes"):
            owned = [lb for lb, _v in samples.get(fam, [])
                     if lb.get("node") == node_label]
            assert not owned, (fam, owned)
        with urllib.request.urlopen(
                f"http://{svc.addr}/debug/capacity", timeout=10) as r:
            cap = json.loads(r.read())
        assert cap == {"enabled": False}
    finally:
        if svc is not None:
            svc.close()
        for nd in nodes:
            nd.shutdown()
