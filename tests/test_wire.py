"""Columnar gossip wire format (net/columnar.py, docs/ingest.md).

Covers the tentpole's correctness contract end to end:

- codec round trip (nil/empty/loaded tx slices, trace-id sidecar,
  full-width R/S scalars) and frame validation;
- the fast Go-JSON materializer is byte-identical to the GoStruct
  encoder (the property that keeps hashes/signatures stable);
- `read_wire_batch` produces the same events from either wire form;
- TCP negotiation: columnar<->columnar moves binary frames,
  columnar->legacy transparently falls back, message-size caps bound
  both framings;
- mixed-format interop: a DETERMINISTIC 3-core gossip script run
  all-legacy, all-columnar, and mixed commits byte-identical blocks,
  trace sidecar included.
"""

import json
import queue
import threading

import pytest

import babble_tpu.gojson as gojson
from babble_tpu import crypto
from babble_tpu.gojson import Timestamp
from babble_tpu.hashgraph.event import (
    Event,
    WireBody,
    WireEvent,
    materialize_wire_event,
)
from babble_tpu.hashgraph.inmem_store import InmemStore
from babble_tpu.net.columnar import (
    ColumnarEvents,
    WIRE_VERSION,
    WireFormatError,
)
from babble_tpu.net.tcp_transport import TCPTransport
from babble_tpu.net.transport import (
    EagerSyncRequest,
    EagerSyncResponse,
    SyncRequest,
    SyncResponse,
    TransportError,
)
from babble_tpu.node.core import Core

N_ORDER = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551


def wire_event(txs=None, idx=1, cid=0, trace_id=0, r=12345, s=67890):
    return WireEvent(
        WireBody(
            transactions=txs,
            self_parent_index=idx - 1,
            other_parent_creator_id=(cid + 1) % 3,
            other_parent_index=0,
            creator_id=cid,
            timestamp=Timestamp(1_700_000_000_000_000_123 + idx),
            index=idx,
        ),
        r=r, s=s, trace_id=trace_id,
    )


# -- codec ---------------------------------------------------------------


def test_codec_round_trip_preserves_wire_dicts():
    wires = [
        wire_event(None, idx=0),
        wire_event([], idx=1),
        wire_event([b"a", b"\x00\xff" * 10, b""], idx=2, trace_id=77),
        wire_event([b"solo"], idx=3, cid=2, r=N_ORDER - 1, s=N_ORDER - 2),
    ]
    cols = ColumnarEvents.from_wire_events(wires)
    back = ColumnarEvents.decode(cols.encode()).to_wire_events()
    assert len(back) == len(wires)
    for orig, got in zip(wires, back):
        assert got.to_dict() == orig.to_dict()
        assert got.trace_id == orig.trace_id


def test_codec_trace_column_absent_when_untraced():
    cols = ColumnarEvents.from_wire_events([wire_event(), wire_event(idx=2)])
    assert cols.trace_ids is None
    # and the frame does not grow a trace column
    n_untraced = len(cols.encode())
    traced = ColumnarEvents.from_wire_events(
        [wire_event(trace_id=5), wire_event(idx=2)])
    assert len(traced.encode()) == n_untraced + 2 * 8


def test_codec_rejects_malformed_frames():
    cols = ColumnarEvents.from_wire_events([wire_event([b"tx"])])
    buf = cols.encode()
    with pytest.raises(WireFormatError):
        ColumnarEvents.decode(b"XXXX" + buf[4:])
    with pytest.raises(WireFormatError):
        ColumnarEvents.decode(buf[:-1])  # truncated
    with pytest.raises(WireFormatError):
        ColumnarEvents.decode(buf + b"\x00")  # trailing junk


# -- fast materializer ---------------------------------------------------


@pytest.mark.parametrize("txs,parents", [
    (None, ["", ""]),
    ([], ["0xAA", ""]),
    ([b"hello", b"\x00\xfe\xff"], ["0xAA", "0xBB"]),
])
def test_materializer_matches_gostruct_encoder(txs, parents):
    key = crypto.key_from_seed(42)
    pub = crypto.pub_key_bytes(key)
    ev = Event.new(txs, parents, pub, 3,
                   timestamp=Timestamp(1_723_400_000_123_456_789))
    ev.sign(key)
    ev.set_wire_info(2, 1, 7, 0)

    m = materialize_wire_event(
        pub, parents[0], parents[1], 3, ev.body.timestamp.ns, txs,
        int(ev.r), int(ev.s), 2, 1, 7, 0)
    # seeded memos match the walked encoder...
    assert m.body.marshal_value() == ev.body.marshal_value()
    assert m.marshal() == ev.marshal()
    assert m.hex() == ev.hex()
    assert m.verify()
    # ...and a from-scratch re-encode (memos dropped) agrees, so the
    # template and the GoStruct walker are the same function.
    m.invalidate()
    assert m.marshal() == ev.marshal()


# -- read path parity ----------------------------------------------------


def _three_cores(seed_base=7000):
    keys = sorted((crypto.key_from_seed(seed_base + i) for i in range(3)),
                  key=lambda k: crypto.pub_key_bytes(k).hex().upper())
    parts = {"0x" + crypto.pub_key_bytes(k).hex().upper(): i
             for i, k in enumerate(keys)}
    cores = [Core(i, k, parts, InmemStore(parts, 10000))
             for i, k in enumerate(keys)]
    for c in cores:
        c.init()
    return keys, parts, cores


def test_read_wire_batch_columnar_matches_legacy():
    _, parts, cores = _three_cores()
    a, b = cores[0], cores[1]
    diff = b.diff(a.known())
    legacy = a.hg.read_wire_batch([e.to_wire() for e in diff])
    cols = ColumnarEvents.from_events(diff)
    columnar = a.hg.read_wire_batch(ColumnarEvents.decode(cols.encode()))
    assert [e.hex() for e in legacy] == [e.hex() for e in columnar]
    for el, ec in zip(legacy, columnar):
        assert el.marshal() == ec.marshal()
        assert el.body.parents == ec.body.parents
        assert ec.verify()


# -- deterministic mixed-format interop ---------------------------------


def _scripted_cluster(monkeypatch, wire_formats, trace=False):
    """Run a fixed gossip script over three Cores, each packing its
    outbound diffs in its own wire format, with deterministic
    timestamps — returns each node's committed blocks as Go-JSON
    bytes. Any two runs of this function must agree byte-for-byte
    regardless of the wire-format mix (the interop contract)."""
    tick = {"ns": 1_700_000_000_000_000_000}

    def fake_now():
        tick["ns"] += 1_000_000
        return Timestamp(tick["ns"])

    monkeypatch.setattr(gojson.Timestamp, "now", staticmethod(fake_now))

    keys, parts, cores = _three_cores()
    blocks = [[] for _ in cores]
    for i, c in enumerate(cores):
        c._commit_callback = blocks[i].append
        c.hg.commit_callback = blocks[i].append

    def hop(dst, src, txn=None):
        diff = cores[src].diff(cores[dst].known())
        payload = cores[src].to_wire_batch(diff, wire_formats[src])
        if txn is not None:
            tid = {txn: 1 << 40} if trace else None
            cores[dst].add_transactions([txn], trace_ids=tid)
        cores[dst].sync(payload)
        cores[dst].run_consensus()

    # fixed script: enough rounds for several blocks to commit
    script = [(0, 1), (1, 2), (2, 0), (1, 0), (0, 2), (2, 1)] * 12
    for i, (dst, src) in enumerate(script):
        hop(dst, src, b"tx %d" % i)

    out = []
    for blist in blocks:
        out.append([
            json.dumps({"r": b.round_received,
                        "txs": [t.hex() for t in (b.transactions or [])]},
                       sort_keys=True)
            for b in blist
        ])
    return out


def test_mixed_cluster_commits_byte_identical_blocks(monkeypatch):
    runs = {}
    for name, fmts in [
        ("legacy", ["gojson"] * 3),
        ("columnar", ["columnar"] * 3),
        ("mixed", ["columnar", "gojson", "columnar"]),
        ("mixed_traced", ["columnar", "gojson", "columnar"]),
    ]:
        runs[name] = _scripted_cluster(
            monkeypatch, fmts, trace=(name == "mixed_traced"))
        # within a run: every node commits the same block sequence up
        # to the in-flight tail (the script ends mid-gossip, so nodes
        # may trail by a pass — byte-identical on the common prefix)
        a, b, c = runs[name]
        m = min(len(a), len(b), len(c))
        assert m > 0, name
        assert a[:m] == b[:m] == c[:m], name
    # across runs: wire format (and the trace sidecar) never leaks
    # into consensus output — the deterministic script makes whole
    # runs comparable byte-for-byte
    assert runs["legacy"] == runs["columnar"] == runs["mixed"] \
        == runs["mixed_traced"]


def test_trace_sidecar_rides_columnar_wire_and_gojson_roundtrip():
    _, parts, cores = _three_cores()
    a, b = cores[0], cores[1]
    # stamp a traced tx into b's next self-event
    b.add_transactions([b"traced"], trace_ids={b"traced": 424242})
    b.sync(a.to_wire_batch(a.diff(b.known()), "columnar"))
    diff = b.diff(a.known())
    assert any(e.trace_id == 424242 for e in diff)
    cols = ColumnarEvents.decode(
        ColumnarEvents.from_events(diff).encode())
    got = a.hg.read_wire_batch(cols)
    assert any(e.trace_id == 424242 for e in got)
    # gojson round trip preserves the sidecar and the signed bytes
    for w in cols.to_wire_events():
        w2 = WireEvent.from_json_obj(json.loads(
            json.dumps(w.to_dict(), default=_b64)))
        assert w2.to_dict() == w.to_dict()
        assert w2.trace_id == w.trace_id


def _b64(obj):
    import base64

    if isinstance(obj, (bytes, bytearray)):
        return base64.b64encode(bytes(obj)).decode()
    raise TypeError


# -- TCP negotiation + framing ------------------------------------------


def _tcp_pair(fmt1="columnar", fmt2="columnar", **kw):
    t1 = TCPTransport("127.0.0.1:0", timeout=2.0, wire_format=fmt1, **kw)
    t2 = TCPTransport("127.0.0.1:0", timeout=2.0, wire_format=fmt2, **kw)
    return t1, t2


def _serve_sync(trans, resp, n=1):
    def loop():
        for _ in range(n):
            try:
                rpc = trans.consumer().get(timeout=5.0)
            except queue.Empty:
                return
            rpc.respond(resp, None)

    threading.Thread(target=loop, daemon=True).start()


def test_tcp_columnar_negotiation_moves_binary_frames():
    t1, t2 = _tcp_pair()
    try:
        resp = SyncResponse(1, events=[wire_event([b"tx"])],
                            known={0: 4})
        _serve_sync(t1, resp)
        out = t2.sync(t1.local_addr(), SyncRequest(0, {0: 1}))
        assert t2._peer_columnar[t1.local_addr()] is True
        assert isinstance(out.events, ColumnarEvents)
        assert out.known == {0: 4}
        got = out.events.to_wire_events()
        assert got[0].to_dict() == wire_event([b"tx"]).to_dict()
        # byte accounting: the payload moved as columnar frames
        rx = t2._byte_counters[("columnar", "rx")].value
        assert rx > 0
    finally:
        t1.close()
        t2.close()


def test_tcp_columnar_to_legacy_falls_back_transparently():
    t1, t2 = _tcp_pair(fmt1="gojson", fmt2="columnar")
    try:
        resp = SyncResponse(1, events=[wire_event([b"tx"])])
        _serve_sync(t1, resp)
        out = t2.sync(t1.local_addr(), SyncRequest(0, {0: 1}))
        # hello negotiated DOWN: the peer answered gojson
        assert t2._peer_columnar[t1.local_addr()] is False
        assert isinstance(out.events, list)
        assert out.events[0].to_dict() == wire_event([b"tx"]).to_dict()

        # and a columnar payload pushed AT the legacy peer downconverts
        _serve_sync(t1, EagerSyncResponse(1, True))
        cols = ColumnarEvents.from_wire_events([wire_event([b"p"], idx=2)])
        got = t2.eager_sync(t1.local_addr(), EagerSyncRequest(0, cols))
        assert got.success is True
    finally:
        t1.close()
        t2.close()


def test_tcp_eager_columnar_round_trip():
    t1, t2 = _tcp_pair()
    got_events = {}
    try:
        def loop():
            rpc = t1.consumer().get(timeout=5.0)
            got_events["events"] = rpc.command.events
            rpc.respond(EagerSyncResponse(1, True), None)

        threading.Thread(target=loop, daemon=True).start()
        cols = ColumnarEvents.from_wire_events(
            [wire_event([b"payload"], trace_id=9)])
        out = t2.eager_sync(t1.local_addr(), EagerSyncRequest(0, cols))
        assert out.success is True
        arrived = got_events["events"]
        assert isinstance(arrived, ColumnarEvents)
        assert arrived.to_wire_events()[0].trace_id == 9
    finally:
        t1.close()
        t2.close()


def test_tcp_message_size_cap_is_enforced():
    t1, t2 = _tcp_pair(max_msg_bytes=512)
    try:
        # Oversized legacy JSON line: the request body itself blows the
        # sender-side cap? No — caps bind on RECEIVE; build a payload
        # the responder cannot frame under 512 bytes.
        resp = SyncResponse(
            1, events=[wire_event([b"x" * 2048])])
        _serve_sync(t1, resp, n=2)
        with pytest.raises(TransportError):
            t2.sync(t1.local_addr(), SyncRequest(0, {0: 1}))
    finally:
        t1.close()
        t2.close()


def test_tcp_legacy_json_line_cap():
    t1, t2 = _tcp_pair(fmt1="gojson", fmt2="gojson", max_msg_bytes=256)
    try:
        resp = SyncResponse(1, events=[wire_event([b"y" * 1024])])
        _serve_sync(t1, resp)
        with pytest.raises(TransportError):
            t2.sync(t1.local_addr(), SyncRequest(0, {0: 1}))
    finally:
        t1.close()
        t2.close()
