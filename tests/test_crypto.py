"""Crypto layer — mirrors reference crypto/crypto_test.go (TestPem) plus
sign/verify round trips."""

import os

from babble_tpu import crypto


def test_sign_verify():
    key = crypto.generate_key()
    digest = crypto.sha256(b"hello")
    r, s = crypto.sign(key, digest)
    pub = crypto.pub_key_from_bytes(crypto.pub_key_bytes(key))
    assert crypto.verify(pub, digest, r, s)
    assert not crypto.verify(pub, crypto.sha256(b"tampered"), r, s)


def test_pub_key_roundtrip():
    key = crypto.key_from_seed(42)
    raw = crypto.pub_key_bytes(key)
    assert len(raw) == 65 and raw[0] == 0x04  # uncompressed point
    pub = crypto.pub_key_from_bytes(raw)
    if crypto.BACKEND == "openssl":
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        assert pub.public_bytes(
            Encoding.X962, PublicFormat.UncompressedPoint) == raw
    else:
        assert pub.public_bytes() == raw


def test_fallback_matches_wire_format():
    """The pure-Python fallback signs/verifies interchangeably with the
    module-level API regardless of which backend is active."""
    from babble_tpu.crypto import _fallback as fb

    key = fb.key_from_seed(42)
    assert fb.pub_key_bytes(key) == crypto.pub_key_bytes(
        crypto.key_from_seed(42))
    digest = crypto.sha256(b"interop")
    r, s = fb.sign(key, digest)
    # Fallback signature verifies under the active backend's verifier.
    pub = crypto.pub_key_from_bytes(fb.pub_key_bytes(key))
    assert crypto.verify(pub, digest, r, s)
    assert not fb.verify(key.pub, crypto.sha256(b"other"), r, s)


def test_fallback_pem_roundtrip(tmp_path):
    from babble_tpu.crypto import _fallback as fb

    key = fb.generate_key()
    pem = fb.key_to_pem(key)
    assert b"EC PRIVATE KEY" in pem
    key2 = fb.key_from_pem(pem)
    assert fb.pub_key_bytes(key) == fb.pub_key_bytes(key2)


def test_deterministic_seed_keys():
    k1 = crypto.key_from_seed(7)
    k2 = crypto.key_from_seed(7)
    assert crypto.pub_key_bytes(k1) == crypto.pub_key_bytes(k2)
    assert crypto.pub_key_bytes(k1) != crypto.pub_key_bytes(crypto.key_from_seed(8))


def test_pem_roundtrip(tmp_path):
    pem = crypto.PemKey(str(tmp_path))
    key = crypto.generate_key()
    pem.write_key(key)
    key2 = pem.read_key()
    assert crypto.pub_key_bytes(key) == crypto.pub_key_bytes(key2)
    with open(os.path.join(str(tmp_path), "priv_key.pem")) as f:
        assert "EC PRIVATE KEY" in f.read()


def test_generate_pem_key():
    dump = crypto.generate_pem_key()
    assert dump.public_key.startswith("0x")
    assert len(dump.public_key) == 2 + 130  # 65 bytes hex
    assert "EC PRIVATE KEY" in dump.private_key


def test_openssl_ctypes_accelerator_parity():
    """When the system libcrypto is loadable, the ctypes accelerator
    must be bit-compatible with the pure-Python fallback: identical
    RFC 6979 signatures, interchangeable verification, and honest
    rejection of bad signatures and off-curve points."""
    from babble_tpu.crypto import _fallback as fb
    from babble_tpu.crypto import _openssl as ossl

    if not ossl.available():
        import pytest

        pytest.skip("system libcrypto not loadable")

    key = fb.key_from_seed(1234)
    digest = crypto.sha256(b"accelerated")
    r, s = ossl.sign(key.d, digest)
    assert (r, s) == fb.sign(key, digest)  # bit-identical nonces
    pub = fb.pub_key_bytes(key)
    assert ossl.verify(pub, digest, r, s)
    assert fb.verify(key.pub, digest, r, s)
    assert not ossl.verify(pub, crypto.sha256(b"other"), r, s)
    assert not ossl.verify(pub, digest, r, s + 1)
    assert not ossl.verify(pub, digest, 0, s)
    # off-curve point: rejected, not crashed
    bad = b"\x04" + b"\x01" * 64
    assert not ossl.verify(bad, digest, r, s)
    # base-point multiplication agrees with the pure-Python ladder
    for k in (1, 2, 0xDEADBEEF, fb.N - 1):
        assert ossl.base_point_x(k) == fb._mult_base(k)[0]


# ---------------------------------------------------- batched verify


def _batch_vectors():
    """Mixed parity corpus for verify_batch (docs/ingest.md "Crypto
    plane"): valid signatures from repeated creators (exercises the
    per-creator grouping), a corrupted s, a high-s encoding (N - s is
    an equally valid ECDSA signature), r >= N and r = 0 range
    rejections, and a malformed creator point (None verdict). Returns
    (pubs, digests, sigs, expected)."""
    from babble_tpu.crypto import _fallback as fb

    keys = [fb.key_from_seed(s) for s in (11, 12, 13)]
    pubs_b = [fb.pub_key_bytes(k) for k in keys]
    pubs, digests, sigs, expected = [], [], [], []
    for i in range(6):
        k = keys[i % 3]
        d = crypto.sha256(b"batch-%d" % i)
        r, s = fb.sign(k, d)
        ok = True
        if i == 2:
            s = (s + 1) % fb.N or 1  # corrupted at position 2
            ok = False
        if i == 4:
            s = fb.N - s  # high-s: still a valid signature
        pubs.append(pubs_b[i % 3])
        digests.append(d)
        sigs.append((r, s))
        expected.append(ok)
    # range rejections on a valid digest
    d = crypto.sha256(b"range")
    r, s = fb.sign(keys[0], d)
    pubs += [pubs_b[0], pubs_b[0]]
    digests += [d, d]
    sigs += [(fb.N + 5, s), (0, s)]
    expected += [False, False]
    # malformed creator point: verdict None (the ingest path leaves
    # the memo unset and re-raises serially)
    pubs.append(b"\x04" + b"\x00" * 64)
    digests.append(d)
    sigs.append((r, s))
    expected.append(None)
    return pubs, digests, sigs, expected


def test_verify_batch_fallback_parity():
    """Pure-python verify_batch (Montgomery-fused inversions) agrees
    with the serial verifier at every batch position."""
    from babble_tpu.crypto import _fallback as fb

    pubs, digests, sigs, expected = _batch_vectors()
    assert fb.verify_batch(pubs, digests, sigs) == expected
    # serial cross-check, position by position
    for pub, d, (r, s), exp in zip(pubs, digests, sigs, expected):
        if exp is None:
            continue
        assert fb.verify(fb.pub_key_from_bytes(pub), d, r, s) is exp


def test_verify_batch_openssl_ctypes_parity():
    """The ctypes batch path (shared EC_KEY per creator) returns the
    identical verdict list."""
    from babble_tpu.crypto import _openssl as ossl

    if not ossl.available():
        import pytest

        pytest.skip("system libcrypto not loadable")
    pubs, digests, sigs, expected = _batch_vectors()
    assert ossl.verify_batch(pubs, digests, sigs) == expected


def test_verify_batch_module_dispatch():
    """The active backend's module-level crypto.verify_batch agrees
    with the serial module-level verifier."""
    pubs, digests, sigs, expected = _batch_vectors()
    assert crypto.verify_batch(pubs, digests, sigs) == expected


def test_verify_batch_identity_point_rejection():
    """Shamir-trick degeneracies: with the d=1 key (Q = G),
    r = (N - z) mod N drives u1*G + u2*Q to the point at infinity —
    the verifier must reject, not crash — and r = z mod N makes
    u1 == u2, forcing the add's doubling branch. Both backends agree."""
    from babble_tpu.crypto import _fallback as fb
    from babble_tpu.crypto import _openssl as ossl

    k1 = fb.key_from_seed(0)
    assert k1.d == 1  # Q == G
    pub = fb.pub_key_bytes(k1)
    d = crypto.sha256(b"degenerate")
    z = int.from_bytes(d, "big") % fb.N
    r_inf = (fb.N - z) % fb.N or 1
    r_dbl = z or 1
    sigs = [(r_inf, 1), (r_dbl, 1)]
    expected = fb.verify_batch([pub, pub], [d, d], sigs)
    assert expected[0] is False  # infinity is a rejection
    for pub_i, d_i, (r, s), exp in zip([pub, pub], [d, d], sigs, expected):
        assert fb.verify(fb.pub_key_from_bytes(pub_i), d_i, r, s) is exp
    if ossl.available():
        assert ossl.verify_batch([pub, pub], [d, d], sigs) == expected


def test_pure_crypto_env_kill_switch(tmp_path):
    """BABBLE_PURE_CRYPTO=1 must pin BACKEND to pure-python (CI's
    no-optional-deps job relies on it to keep the fallback exercised)."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-c",
         "from babble_tpu import crypto; print(crypto.BACKEND)"],
        capture_output=True, text=True,
        env={**os.environ, "BABBLE_PURE_CRYPTO": "1"})
    assert out.stdout.strip() == "pure-python", out.stderr
