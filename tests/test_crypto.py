"""Crypto layer — mirrors reference crypto/crypto_test.go (TestPem) plus
sign/verify round trips."""

import os

from babble_tpu import crypto


def test_sign_verify():
    key = crypto.generate_key()
    digest = crypto.sha256(b"hello")
    r, s = crypto.sign(key, digest)
    pub = crypto.pub_key_from_bytes(crypto.pub_key_bytes(key))
    assert crypto.verify(pub, digest, r, s)
    assert not crypto.verify(pub, crypto.sha256(b"tampered"), r, s)


def test_pub_key_roundtrip():
    key = crypto.key_from_seed(42)
    raw = crypto.pub_key_bytes(key)
    assert len(raw) == 65 and raw[0] == 0x04  # uncompressed point
    pub = crypto.pub_key_from_bytes(raw)
    if crypto.BACKEND == "openssl":
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        assert pub.public_bytes(
            Encoding.X962, PublicFormat.UncompressedPoint) == raw
    else:
        assert pub.public_bytes() == raw


def test_fallback_matches_wire_format():
    """The pure-Python fallback signs/verifies interchangeably with the
    module-level API regardless of which backend is active."""
    from babble_tpu.crypto import _fallback as fb

    key = fb.key_from_seed(42)
    assert fb.pub_key_bytes(key) == crypto.pub_key_bytes(
        crypto.key_from_seed(42))
    digest = crypto.sha256(b"interop")
    r, s = fb.sign(key, digest)
    # Fallback signature verifies under the active backend's verifier.
    pub = crypto.pub_key_from_bytes(fb.pub_key_bytes(key))
    assert crypto.verify(pub, digest, r, s)
    assert not fb.verify(key.pub, crypto.sha256(b"other"), r, s)


def test_fallback_pem_roundtrip(tmp_path):
    from babble_tpu.crypto import _fallback as fb

    key = fb.generate_key()
    pem = fb.key_to_pem(key)
    assert b"EC PRIVATE KEY" in pem
    key2 = fb.key_from_pem(pem)
    assert fb.pub_key_bytes(key) == fb.pub_key_bytes(key2)


def test_deterministic_seed_keys():
    k1 = crypto.key_from_seed(7)
    k2 = crypto.key_from_seed(7)
    assert crypto.pub_key_bytes(k1) == crypto.pub_key_bytes(k2)
    assert crypto.pub_key_bytes(k1) != crypto.pub_key_bytes(crypto.key_from_seed(8))


def test_pem_roundtrip(tmp_path):
    pem = crypto.PemKey(str(tmp_path))
    key = crypto.generate_key()
    pem.write_key(key)
    key2 = pem.read_key()
    assert crypto.pub_key_bytes(key) == crypto.pub_key_bytes(key2)
    with open(os.path.join(str(tmp_path), "priv_key.pem")) as f:
        assert "EC PRIVATE KEY" in f.read()


def test_generate_pem_key():
    dump = crypto.generate_pem_key()
    assert dump.public_key.startswith("0x")
    assert len(dump.public_key) == 2 + 130  # 65 bytes hex
    assert "EC PRIVATE KEY" in dump.private_key


def test_openssl_ctypes_accelerator_parity():
    """When the system libcrypto is loadable, the ctypes accelerator
    must be bit-compatible with the pure-Python fallback: identical
    RFC 6979 signatures, interchangeable verification, and honest
    rejection of bad signatures and off-curve points."""
    from babble_tpu.crypto import _fallback as fb
    from babble_tpu.crypto import _openssl as ossl

    if not ossl.available():
        import pytest

        pytest.skip("system libcrypto not loadable")

    key = fb.key_from_seed(1234)
    digest = crypto.sha256(b"accelerated")
    r, s = ossl.sign(key.d, digest)
    assert (r, s) == fb.sign(key, digest)  # bit-identical nonces
    pub = fb.pub_key_bytes(key)
    assert ossl.verify(pub, digest, r, s)
    assert fb.verify(key.pub, digest, r, s)
    assert not ossl.verify(pub, crypto.sha256(b"other"), r, s)
    assert not ossl.verify(pub, digest, r, s + 1)
    assert not ossl.verify(pub, digest, 0, s)
    # off-curve point: rejected, not crashed
    bad = b"\x04" + b"\x01" * 64
    assert not ossl.verify(bad, digest, r, s)
    # base-point multiplication agrees with the pure-Python ladder
    for k in (1, 2, 0xDEADBEEF, fb.N - 1):
        assert ossl.base_point_x(k) == fb._mult_base(k)[0]


def test_pure_crypto_env_kill_switch(tmp_path):
    """BABBLE_PURE_CRYPTO=1 must pin BACKEND to pure-python (CI's
    no-optional-deps job relies on it to keep the fallback exercised)."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-c",
         "from babble_tpu import crypto; print(crypto.BACKEND)"],
        capture_output=True, text=True,
        env={**os.environ, "BABBLE_PURE_CRYPTO": "1"})
    assert out.stdout.strip() == "pure-python", out.stderr
