"""Crypto layer — mirrors reference crypto/crypto_test.go (TestPem) plus
sign/verify round trips."""

import os

from babble_tpu import crypto


def test_sign_verify():
    key = crypto.generate_key()
    digest = crypto.sha256(b"hello")
    r, s = crypto.sign(key, digest)
    pub = crypto.pub_key_from_bytes(crypto.pub_key_bytes(key))
    assert crypto.verify(pub, digest, r, s)
    assert not crypto.verify(pub, crypto.sha256(b"tampered"), r, s)


def test_pub_key_roundtrip():
    key = crypto.key_from_seed(42)
    raw = crypto.pub_key_bytes(key)
    assert len(raw) == 65 and raw[0] == 0x04  # uncompressed point
    pub = crypto.pub_key_from_bytes(raw)
    if crypto.BACKEND == "openssl":
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        assert pub.public_bytes(
            Encoding.X962, PublicFormat.UncompressedPoint) == raw
    else:
        assert pub.public_bytes() == raw


def test_fallback_matches_wire_format():
    """The pure-Python fallback signs/verifies interchangeably with the
    module-level API regardless of which backend is active."""
    from babble_tpu.crypto import _fallback as fb

    key = fb.key_from_seed(42)
    assert fb.pub_key_bytes(key) == crypto.pub_key_bytes(
        crypto.key_from_seed(42))
    digest = crypto.sha256(b"interop")
    r, s = fb.sign(key, digest)
    # Fallback signature verifies under the active backend's verifier.
    pub = crypto.pub_key_from_bytes(fb.pub_key_bytes(key))
    assert crypto.verify(pub, digest, r, s)
    assert not fb.verify(key.pub, crypto.sha256(b"other"), r, s)


def test_fallback_pem_roundtrip(tmp_path):
    from babble_tpu.crypto import _fallback as fb

    key = fb.generate_key()
    pem = fb.key_to_pem(key)
    assert b"EC PRIVATE KEY" in pem
    key2 = fb.key_from_pem(pem)
    assert fb.pub_key_bytes(key) == fb.pub_key_bytes(key2)


def test_deterministic_seed_keys():
    k1 = crypto.key_from_seed(7)
    k2 = crypto.key_from_seed(7)
    assert crypto.pub_key_bytes(k1) == crypto.pub_key_bytes(k2)
    assert crypto.pub_key_bytes(k1) != crypto.pub_key_bytes(crypto.key_from_seed(8))


def test_pem_roundtrip(tmp_path):
    pem = crypto.PemKey(str(tmp_path))
    key = crypto.generate_key()
    pem.write_key(key)
    key2 = pem.read_key()
    assert crypto.pub_key_bytes(key) == crypto.pub_key_bytes(key2)
    with open(os.path.join(str(tmp_path), "priv_key.pem")) as f:
        assert "EC PRIVATE KEY" in f.read()


def test_generate_pem_key():
    dump = crypto.generate_pem_key()
    assert dump.public_key.startswith("0x")
    assert len(dump.public_key) == 2 + 130  # 65 bytes hex
    assert "EC PRIVATE KEY" in dump.private_key
