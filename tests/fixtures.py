"""Hand-drawn DAG fixtures used as the consensus-parity oracle.

These re-create the reference's test graphs (reference
hashgraph/hashgraph_test.go: initHashgraph:80, initRoundHashgraph:383,
initConsensusHashgraph:912, initFunkyHashgraph:1464) via a `play` DSL:
each play appends one event (creator, creator-index, named self/other
parents, payload) to the graph in insertion order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from babble_tpu import crypto
from babble_tpu.gojson import Timestamp
from babble_tpu.hashgraph import Event, Hashgraph, InmemStore

CACHE_SIZE = 100


@dataclass
class SimNode:
    id: int
    key: object
    pub: bytes
    pub_hex: str
    events: List[Event] = field(default_factory=list)


def make_nodes(n: int, seed_base: int = 1000) -> List[SimNode]:
    nodes = []
    for i in range(n):
        key = crypto.key_from_seed(seed_base + i)
        pub = crypto.pub_key_bytes(key)
        nodes.append(SimNode(id=i, key=key, pub=pub, pub_hex="0x" + pub.hex().upper()))
    return nodes


@dataclass
class Play:
    to: int
    index: int
    self_parent: str
    other_parent: str
    name: str
    payload: Optional[List[bytes]] = None  # None -> empty list (Go [][]byte{})


class GraphBuilder:
    """Builds events from plays; timestamps increase monotonically so
    median-timestamp consensus ordering is deterministic across runs."""

    def __init__(self, n: int, seed_base: int = 1000):
        self.nodes = make_nodes(n, seed_base)
        self.index: Dict[str, str] = {}
        self.ordered_events: List[Event] = []
        self._clock = 1_600_000_000_000_000_000  # arbitrary fixed epoch ns

    def _next_ts(self) -> Timestamp:
        self._clock += 1_000_000  # 1ms
        return Timestamp(self._clock)

    def add_initial(self, name: str, node_i: int, payload: Optional[List[bytes]] = None):
        node = self.nodes[node_i]
        ev = Event.new(
            payload if payload is not None else [],
            ["", ""],
            node.pub,
            0,
            timestamp=self._next_ts(),
        )
        ev.sign(node.key)
        node.events.append(ev)
        self.index[name] = ev.hex()
        self.ordered_events.append(ev)
        return ev

    def play(self, p: Play):
        node = self.nodes[p.to]
        ev = Event.new(
            p.payload if p.payload is not None else [],
            [self.index.get(p.self_parent, ""), self.index.get(p.other_parent, "")],
            node.pub,
            p.index,
            timestamp=self._next_ts(),
        )
        ev.sign(node.key)
        node.events.append(ev)
        self.index[p.name] = ev.hex()
        self.ordered_events.append(ev)
        return ev

    def participants(self) -> Dict[str, int]:
        return {node.pub_hex: node.id for node in self.nodes}

    def make_hashgraph(self, store=None) -> Hashgraph:
        participants = self.participants()
        if store is None:
            store = InmemStore(participants, CACHE_SIZE)
        return Hashgraph(participants, store)

    def get_name(self, hash_: str) -> str:
        for name, h in self.index.items():
            if h == hash_:
                return name
        return ""


def build_basic_graph() -> Tuple[Hashgraph, GraphBuilder]:
    """Ancestry fixture — reference hashgraph_test.go:66-133.

    |  e12  |
    |   | \\ |
    |  s10   e20
    |   | / |
    |   /   |
    | / |   |
    s00 |  s20
    |   |   |
    e01 |   |
    | \\ |   |
    e0  e1  e2
    0   1   2

    Events are installed without the insert pipeline (coordinates +
    store + first-descendant update only), as the reference does.
    """
    b = GraphBuilder(3)
    for i in range(3):
        b.add_initial(f"e{i}", i)
    for p in [
        Play(0, 1, "e0", "e1", "e01"),
        Play(2, 1, "e2", "", "s20"),
        Play(1, 1, "e1", "", "s10"),
        Play(0, 2, "e01", "", "s00"),
        Play(2, 2, "s20", "s00", "e20"),
        Play(1, 2, "s10", "e20", "e12"),
    ]:
        b.play(p)

    h = b.make_hashgraph()
    for ev in b.ordered_events:
        h._init_event_coordinates(ev)
        h.store.set_event(ev)
        h._update_ancestor_first_descendant(ev)
    return h, b


def build_round_graph() -> Tuple[Hashgraph, GraphBuilder]:
    """Rounds/witness fixture — reference hashgraph_test.go:365-427.

    |  s11  |
    |   |   |
    |   f1  |
    |  /|   |
    | / s10 |
    |/  |   |
    e02 |   |
    | \\ |   |
    |   \\   |
    |   | \\ |
    s00 |  e21
    |   | / |
    |  e10  s20
    | / |   |
    e0  e1  e2
    0   1    2
    """
    b = GraphBuilder(3)
    for i in range(3):
        b.add_initial(f"e{i}", i)
    for p in [
        Play(1, 1, "e1", "e0", "e10"),
        Play(2, 1, "e2", "", "s20"),
        Play(0, 1, "e0", "", "s00"),
        Play(2, 2, "s20", "e10", "e21"),
        Play(0, 2, "s00", "e21", "e02"),
        Play(1, 2, "e10", "", "s10"),
        Play(1, 3, "s10", "e02", "f1"),
        Play(1, 4, "f1", "", "s11", [b"abc"]),
    ]:
        b.play(p)

    h = b.make_hashgraph()
    for ev in b.ordered_events:
        h.insert_event(ev, True)
    return h, b


CONSENSUS_PLAYS = [
    Play(1, 1, "e1", "e0", "e10"),
    Play(2, 1, "e2", "e10", "e21", [b"e21"]),
    Play(2, 2, "e21", "", "e21b"),
    Play(0, 1, "e0", "e21b", "e02"),
    Play(1, 2, "e10", "e02", "f1"),
    Play(1, 3, "f1", "", "f1b", [b"f1b"]),
    Play(0, 2, "e02", "f1b", "f0"),
    Play(2, 3, "e21b", "f1b", "f2"),
    Play(1, 4, "f1b", "f0", "f10"),
    Play(2, 4, "f2", "f10", "f21"),
    Play(0, 3, "f0", "f21", "f02"),
    Play(0, 4, "f02", "", "f02b", [b"e21"]),
    Play(1, 5, "f10", "f02b", "g1"),
    Play(0, 5, "f02b", "g1", "g0"),
    Play(2, 5, "f21", "g1", "g2"),
    Play(1, 6, "g1", "g0", "g10"),
    Play(0, 6, "g0", "f21", "o02"),
    Play(2, 6, "g2", "g10", "g21"),
    Play(0, 7, "o02", "g21", "g02"),
    Play(1, 7, "g10", "g02", "h1"),
    Play(0, 8, "g02", "h1", "h0"),
    Play(2, 7, "g21", "h1", "h2"),
]


def build_consensus_graph(store=None) -> Tuple[Hashgraph, GraphBuilder]:
    """Fame/order fixture (25 events / 3 nodes) — reference
    hashgraph_test.go:866-983."""
    b = GraphBuilder(3)
    for i in range(3):
        b.add_initial(f"e{i}", i)
    for p in CONSENSUS_PLAYS:
        b.play(p)

    h = b.make_hashgraph(store=store)
    for ev in b.ordered_events:
        h.insert_event(ev, True)
    return h, b


FUNKY_PLAYS = [
    Play(2, 1, "w02", "w03", "a23", [b"a23"]),
    Play(1, 1, "w01", "a23", "a12", [b"a12"]),
    Play(0, 1, "w00", "", "a00", [b"a00"]),
    Play(1, 2, "a12", "a00", "a10", [b"a10"]),
    Play(2, 2, "a23", "a12", "a21", [b"a21"]),
    Play(3, 1, "w03", "a21", "w13", [b"w13"]),
    Play(2, 3, "a21", "w13", "w12", [b"w12"]),
    Play(1, 3, "a10", "w12", "w11", [b"w11"]),
    Play(0, 2, "a00", "w11", "w10", [b"w10"]),
    Play(2, 4, "w12", "w11", "b21", [b"b21"]),
    Play(3, 2, "w13", "b21", "w23", [b"w23"]),
    Play(1, 4, "w11", "w23", "w21", [b"w21"]),
    Play(0, 3, "w10", "", "b00", [b"b00"]),
    Play(1, 5, "w21", "b00", "c10", [b"c10"]),
    Play(2, 5, "b21", "c10", "w22", [b"w22"]),
    Play(0, 4, "b00", "w22", "w20", [b"w20"]),
    Play(1, 6, "c10", "w20", "w31", [b"w31"]),
    Play(2, 6, "w22", "w31", "w32", [b"w32"]),
    Play(0, 5, "w20", "w32", "w30", [b"w30"]),
    Play(3, 3, "w23", "w32", "w33", [b"w33"]),
    Play(1, 7, "w31", "w33", "d13", [b"d13"]),
    Play(0, 6, "w30", "d13", "w40", [b"w40"]),
    Play(1, 8, "d13", "w40", "w41", [b"w41"]),
    Play(2, 7, "w32", "w41", "w42", [b"w42"]),
    Play(3, 4, "w33", "w42", "w43", [b"w43"]),
    Play(2, 8, "w42", "w43", "e23", [b"e23"]),
    Play(1, 9, "w41", "e23", "w51", [b"w51"]),
]


def build_funky_graph() -> Tuple[Hashgraph, GraphBuilder]:
    """Irregular-rounds fixture (4 nodes / 32 events) incl. a coin round —
    reference hashgraph_test.go:1407-1533."""
    b = GraphBuilder(4)
    for i in range(4):
        b.add_initial(f"w0{i}", i, [f"w0{i}".encode()])
    for p in FUNKY_PLAYS:
        b.play(p)

    h = b.make_hashgraph()
    for ev in b.ordered_events:
        h.insert_event(ev, True)
    return h, b


def build_coin_graph(extra_rounds: int = 3) -> GraphBuilder:
    """The funky graph extended with a gossip ring so the coin round
    RESOLVES: w00's fame cannot be decided by round 4 (the normal
    rounds stay split), so round-4 witnesses cast coin votes
    (diff % n == 0, reference hashgraph.go:703-709), and the round-5
    tally decides from those coin-influenced votes. With the coin
    forced to 1 the graph decides w00 famous; forced to 0 it stays
    undecided forever (the hashgraph coin-round liveness hole) — both
    outcomes are topology-deterministic, which is what makes this
    testable even though real coin bits depend on event signatures.

    Returns the builder only (no consensus run): callers choose the
    engine and the coin regime."""
    b = GraphBuilder(4)
    for i in range(4):
        b.add_initial(f"w0{i}", i, [f"w0{i}".encode()])
    heads = {0: "w00", 1: "w01", 2: "w02", 3: "w03"}
    idx = {0: 0, 1: 0, 2: 0, 3: 0}
    for p in FUNKY_PLAYS:
        b.play(p)
        heads[p.to] = p.name
        idx[p.to] = p.index
    k = 0
    for _ in range(extra_rounds):
        for c, p in ((3, 1), (1, 3), (0, 2), (2, 0)):
            idx[c] += 1
            name = f"z{k}"
            b.play(Play(c, idx[c], heads[c], heads[p], name,
                        [name.encode()]))
            heads[c] = name
            k += 1
    return b
