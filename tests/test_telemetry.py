"""Unified telemetry (docs/observability.md): registry semantics
(counter/gauge/histogram bucket math, label handling, concurrent
increments), Prometheus text render/parse round trip, the span ring +
Chrome trace export, structured JSON logging, and the live /metrics +
/debug/trace endpoints on a gossiping node."""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request

import pytest

from babble_tpu.telemetry import (
    JsonLogFormatter,
    Registry,
    SpanRing,
    render_merged,
)
from babble_tpu.telemetry import promtext
from babble_tpu.service import Service

from test_node import check_gossip, make_nodes, run_gossip


# ------------------------------------------------------------ registry


def test_counter_inc_and_value():
    reg = Registry()
    c = reg.counter("x_total", "help", node="0")
    assert c.value == 0
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_labels_identify_children():
    reg = Registry()
    a = reg.counter("x_total", node="0")
    b = reg.counter("x_total", node="1")
    # Same name + same labels = the same child; different labels or
    # a different ordering of the same labels do what you expect.
    assert reg.counter("x_total", node="0") is a
    assert a is not b
    g = reg.gauge("y", peer="p", node="0")
    assert reg.gauge("y", node="0", peer="p") is g


def test_type_conflict_rejected():
    reg = Registry()
    reg.counter("x_total")
    with pytest.raises(ValueError):
        reg.gauge("x_total")


def test_gauge_set_and_callback():
    reg = Registry()
    g = reg.gauge("g")
    g.set(4)
    assert g.value == 4
    g.set_fn(lambda: 9)
    assert g.value == 9
    # A raising callback reads as 0 instead of failing the scrape.
    g.set_fn(lambda: 1 / 0)
    assert g.value == 0


def test_histogram_bucket_math():
    reg = Registry()
    h = reg.histogram("h_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 5.0, 50.0):
        h.observe(v)
    snap = h.snapshot()
    # le is an INCLUSIVE upper bound: 0.1 lands in the first bucket.
    assert snap.counts == (2, 1, 1, 1)  # [<=0.1, <=1, <=10, +Inf]
    assert snap.count == 5
    assert snap.sum == pytest.approx(55.65)


def test_histogram_quantiles_interpolate():
    reg = Registry()
    h = reg.histogram("h_seconds", buckets=(1.0, 2.0, 4.0))
    for _ in range(100):
        h.observe(1.5)  # all in the (1, 2] bucket
    # p50 interpolates to the middle of the bucket, p100 to its top.
    assert h.quantile(0.5) == pytest.approx(1.5)
    assert h.quantile(1.0) == pytest.approx(2.0)
    # Overflow observations report the last finite bound.
    h2 = reg.histogram("h2_seconds", buckets=(1.0,))
    h2.observe(99.0)
    assert h2.quantile(0.99) == 1.0
    # Empty histogram: 0, not an exception.
    assert reg.histogram("h3_seconds").quantile(0.5) == 0.0


def test_histogram_snapshot_delta_and_merge():
    reg = Registry()
    h = reg.histogram("h_seconds", buckets=(1.0, 2.0))
    h.observe(0.5)
    before = h.snapshot()
    h.observe(0.5)
    h.observe(1.5)
    delta = h.snapshot() - before
    assert delta.count == 2 and delta.counts == (1, 1, 0)
    merged = delta.merge(before)
    assert merged.count == 3 and merged.sum == pytest.approx(2.5)


def test_concurrent_increments_lose_nothing():
    """Gossip, RPC, and consensus threads hit the same counters: plain
    `+=` drops updates under GIL preemption; the per-instrument lock
    must not."""
    reg = Registry()
    c = reg.counter("x_total")
    h = reg.histogram("h_seconds")
    n_threads, per_thread = 8, 5000

    def work():
        for _ in range(per_thread):
            c.inc()
            h.observe(0.01)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per_thread
    assert h.count == n_threads * per_thread


# ------------------------------------------------- render / parse


def test_render_parse_round_trip():
    reg = Registry()
    reg.counter("c_total", "a counter", node="0").inc(3)
    reg.gauge("g", node="0", peer='tricky"addr\\1').set(-2.5)
    h = reg.histogram("h_seconds", "latency", node="0")
    h.observe(0.003)
    h.observe(0.7)
    text = reg.render()
    samples, types = promtext.parse(text)
    assert types == {"c_total": "counter", "g": "gauge",
                     "h_seconds": "histogram"}
    assert samples["c_total"] == [({"node": "0"}, 3.0)]
    (labels, value), = samples["g"]
    assert labels == {"node": "0", "peer": 'tricky"addr\\1'}
    assert value == -2.5
    snap = promtext.histogram_snapshot(samples, "h_seconds")
    assert snap.count == 2
    assert snap.sum == pytest.approx(0.703)
    # The rebuilt snapshot carries the same bucket math.
    direct = h.snapshot()
    assert snap.counts == direct.counts


def test_parse_rejects_garbage():
    with pytest.raises(ValueError):
        promtext.parse("this is not { a metric\n")
    with pytest.raises(ValueError):
        promtext.parse('x{le=nope} 1\n')


def test_check_series_reports_missing():
    reg = Registry()
    reg.counter("present_total").inc()
    reg.histogram("lat_seconds").observe(0.1)
    samples, _ = promtext.parse(reg.render())
    missing = promtext.check_series(
        samples, ["present_total", "lat_seconds", "absent_total"])
    assert missing == ["absent_total"]


def test_render_merged_deduplicates_families():
    """The /metrics handler merges the process-global registry with
    the node's own: a family present in both must render exactly one
    TYPE line (a duplicate family is an invalid exposition)."""
    a, b = Registry(), Registry()
    a.counter("shared_total", node="0").inc(1)
    b.counter("shared_total", node="1").inc(2)
    b.counter("only_b_total").inc(5)
    text = render_merged(a, b)
    assert text.count("# TYPE shared_total counter") == 1
    samples, _ = promtext.parse(text)
    assert sorted(v for _, v in samples["shared_total"]) == [1.0, 2.0]
    assert samples["only_b_total"] == [({}, 5.0)]
    a.gauge("clash")
    b.counter("clash")
    with pytest.raises(ValueError):
        render_merged(a, b)


# ------------------------------------------------------- span ring


def test_span_ring_is_bounded():
    ring = SpanRing(16)
    for i in range(100):
        with ring.span("s", cat="test", i=i):
            pass
    assert len(ring) == 16
    # The ring keeps the LAST N spans.
    assert [sp["args"]["i"] for sp in ring.snapshot()] == list(
        range(84, 100))


def test_span_ring_disabled_is_noop():
    ring = SpanRing(0)
    with ring.span("s") as rec:
        rec["outcome"] = "ok"  # call sites never branch on capacity
    assert len(ring) == 0
    assert ring.to_chrome_trace()["traceEvents"]  # metadata only
    assert ring.record("x", 0, 1) == 0


def test_span_records_outcome_and_error():
    ring = SpanRing(8)
    with ring.span("good", cat="c") as rec:
        rec["outcome"] = "ok"
        seen_id = rec["span_id"]  # pre-assigned for log correlation
    with pytest.raises(RuntimeError):
        with ring.span("bad", cat="c"):
            raise RuntimeError("boom")
    good, bad = ring.snapshot()
    assert good["id"] == seen_id
    assert good["args"]["outcome"] == "ok"
    assert bad["args"]["outcome"] == "error"
    assert bad["t1"] >= bad["t0"]


def test_chrome_trace_shape():
    """The export must be loadable Chrome trace-event JSON (what
    Perfetto's JSON importer accepts): an object with a traceEvents
    list, complete events with name/ph/ts/dur/pid/tid, and
    process/thread name metadata."""
    ring = SpanRing(8)
    with ring.span("sync", cat="sync", batch=3):
        pass
    with ring.span("commit", cat="commit", round=1):
        pass
    doc = json.loads(json.dumps(ring.to_chrome_trace(pid=7)))
    events = doc["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    ms = [e for e in events if e["ph"] == "M"]
    assert len(xs) == 2
    for e in xs:
        assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["pid"] == 7 and e["dur"] >= 0
    assert any(m["name"] == "process_name" for m in ms)
    thread_names = {m["args"]["name"] for m in ms
                    if m["name"] == "thread_name"}
    assert thread_names == {"sync", "commit"}
    # Distinct categories get distinct lanes.
    assert len({e["tid"] for e in xs}) == 2


# -------------------------------------------------- JSON logging


def test_json_log_formatter():
    fmt = JsonLogFormatter(node_id=3)
    rec = logging.LogRecord(
        "babble_tpu", logging.INFO, "node.py", 1,
        "fast-forward from %s: %d frame events", ("addr1", 9), None)
    rec.span_id = 42
    obj = json.loads(fmt.format(rec))
    assert obj["node"] == 3
    assert obj["level"] == "info"
    assert obj["logger"] == "babble_tpu"
    assert obj["msg"] == "fast-forward from addr1: 9 frame events"
    assert obj["span_id"] == 42
    assert obj["ts"].endswith("Z")
    # Exceptions serialize into the line instead of a traceback dump.
    try:
        raise ValueError("boom")
    except ValueError:
        import sys

        rec2 = logging.LogRecord(
            "babble_tpu", logging.ERROR, "x", 1, "failed", (),
            sys.exc_info())
    obj2 = json.loads(fmt.format(rec2))
    assert "ValueError: boom" in obj2["exc"]


# ------------------------------------------- live node endpoints


REQUIRED_SERIES = [
    "babble_commit_latency_seconds",
    "babble_gossip_rtt_seconds",
    "babble_breaker_state",
    "babble_engine_pass_seconds",
    "babble_phase_seconds",
    "babble_sync_requests_total",
    "babble_commit_blocks_total",
    "babble_last_consensus_round",
    "babble_engine_backlog",
]


def test_metrics_and_trace_endpoints():
    nodes = make_nodes(4, "inmem")
    service = Service("127.0.0.1:0", nodes[0])
    service.serve_async()
    try:
        run_gossip(nodes, target_round=3, shutdown=False)

        # The submit->commit histogram samples only txs THIS node
        # stamped — at round 3 node 0's own submissions may still be a
        # round away from delivery, so keep feeding it and re-scrape
        # until a sample lands (bounded).
        deadline = time.monotonic() + 30.0
        while True:
            with urllib.request.urlopen(
                    f"http://{service.addr}/metrics", timeout=5) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            samples, types = promtext.parse(text)  # valid exposition
            lat = promtext.histogram_snapshot(
                samples, "babble_commit_latency_seconds")
            if lat.count > 0 or time.monotonic() > deadline:
                break
            nodes[0].submit_tx(b"latency probe tx")
            time.sleep(0.2)
        assert promtext.check_series(samples, REQUIRED_SERIES) == []
        assert types["babble_commit_latency_seconds"] == "histogram"
        assert types["babble_breaker_state"] == "gauge"

        # The submit->commit histogram actually observed this node's
        # committed transactions, and the scrape-side quantile math
        # reproduces sane values.
        assert lat.count > 0
        assert 0 < lat.quantile(0.5) <= lat.quantile(0.99)

        # Per-peer RTT series carry peer + leg labels.
        rtt_labels = [lb for lb, _ in
                      samples["babble_gossip_rtt_seconds_count"]]
        # Outbound legs: pull/push from the reference loop, plus the
        # plumtree planes (eager pushes + graft pulls, docs/gossip.md).
        assert {lb["leg"] for lb in rtt_labels} <= {
            "pull", "push", "eager", "graft", "ihave"}
        assert all(lb["peer"] for lb in rtt_labels)

        # /debug/trace: Perfetto-loadable Chrome trace JSON with the
        # consensus/sync/commit lanes populated by real gossip.
        with urllib.request.urlopen(
                f"http://{service.addr}/debug/trace", timeout=5) as r:
            assert r.status == 200
            doc = json.loads(r.read())
        events = doc["traceEvents"]
        cats = {e["cat"] for e in events if e.get("ph") == "X"}
        assert {"sync", "consensus", "commit", "gossip"} <= cats
        assert len(events) <= nodes[0].trace.capacity + 16  # bounded

        # get_stats keeps its legacy shape while reading through the
        # registry (tests and the bench depend on these keys).
        stats = nodes[0].get_stats()
        for key in ("sync_rate", "fast_forwards", "engine_state",
                    "last_consensus_round", "events_per_second"):
            assert key in stats
        assert 0.0 <= float(stats["sync_rate"]) <= 1.0

        check_gossip(nodes)
    finally:
        for nd in nodes:
            nd.shutdown()
        service.close()


def test_unknown_path_is_json_404():
    nodes = make_nodes(2, "inmem")
    service = Service("127.0.0.1:0", nodes[0])
    service.serve_async()
    try:
        for path, method in (("/no/such/path", "GET"),
                             ("/no/such/path", "POST")):
            req = urllib.request.Request(
                f"http://{service.addr}{path}", method=method,
                data=b"x" if method == "POST" else None)
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=5)
            assert err.value.code == 404
            body = json.loads(err.value.read())
            assert body["error"] == "unknown path"
            assert body["path"] == path
    finally:
        for nd in nodes:
            nd.shutdown()
        service.close()


def test_per_node_registries_are_fresh():
    """A new Node's counters start at zero even after other nodes ran
    in this process — the per-node registry is what keeps the legacy
    sync_requests/sync_errors attribute semantics exact."""
    nodes = make_nodes(2, "inmem")
    try:
        assert nodes[0].sync_requests == 0
        assert nodes[0].sync_errors == 0
        assert nodes[0].fast_forwards == 0
        assert nodes[0].registry is not nodes[1].registry
    finally:
        for nd in nodes:
            nd.shutdown()


def test_promtext_cli_checker(capsys, monkeypatch):
    """The CI pipe: `curl /metrics | python -m ...promtext --require
    name` exits non-zero on a malformed scrape or a missing series."""
    import io

    reg = Registry()
    reg.counter("babble_sync_requests_total", node="0").inc()
    text = reg.render()

    monkeypatch.setattr("sys.stdin", io.StringIO(text))
    assert promtext.main(["--require", "babble_sync_requests_total"]) == 0
    monkeypatch.setattr("sys.stdin", io.StringIO(text))
    assert promtext.main(["--require", "babble_missing_total"]) == 1
    monkeypatch.setattr("sys.stdin", io.StringIO("garbage { line\n"))
    assert promtext.main([]) == 1
