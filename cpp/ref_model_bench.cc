// Conservative native stand-in for the reference Go engine's wall-clock
// at the north-star size (n=1024 peers, e=100k events): a C++
// reimplementation of the reference's insert + DivideRounds data path
// (hashgraph.go:448-530 InitEventCoordinates /
// UpdateAncestorFirstDescendant; :285-339 Round/RoundInc; :170-200
// StronglySee), driven by the same synthetic uniform-gossip schedule
// the Python/TPU north-star benchmark uses.
//
// Every modeling choice is conservative — i.e. makes THIS model faster
// than real Go, so the TPU-vs-Go multiplier derived from it is a lower
// bound:
//   - events live in a flat vector indexed by int id; the reference
//     keys an LRU cache by hex strings (map + string hashing + GC).
//   - rounds are computed once per event in topological order; the
//     reference rescans its undetermined list every sync (cache hits,
//     but still loop + map traffic).
//   - DecideFame votes are computed once per witness pair via the
//     coordinate shortcut (the reference walks hash-keyed caches), and
//     the per-sync DecideRoundReceived rescan of the undetermined set
//     uses one O(n) coordinate compare per candidate round where the
//     reference does cached ancestry DFS walks per famous witness.
//   - consensus runs once per 64-event batch; the reference runs it
//     once per sync (typically 1-20 events).
//   - no signature verification (the Go node verifies per insert).
//   - the final total-order sort and block assembly are omitted.
//
// Build: g++ -O3 -march=native -o ref_model_bench ref_model_bench.cc
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <random>
#include <vector>

static constexpr int32_t INT32_MAX_ = 2147483647;

int main(int argc, char** argv) {
  const int n = argc > 1 ? atoi(argv[1]) : 1024;
  const int e_tot = argc > 2 ? atoi(argv[2]) : 100000;
  const int sm = 2 * n / 3 + 1;

  // Synthetic uniform gossip schedule (ops/dag.py synthetic_dag's
  // process: each event's creator is random; other-parent is a random
  // other peer's current head).
  std::mt19937 rng(1);
  std::uniform_int_distribution<int> pick(0, n - 1);

  struct Ev {
    int32_t creator, index, self_parent, other_parent, round;
    bool witness;
    std::vector<int32_t> la, fd;  // lastAncestors / firstDescendants
  };
  std::vector<Ev> evs(e_tot);
  std::vector<int32_t> head(n, -1), idx(n, 0);
  // Per-creator chains give O(1) ancestor resolution by (creator,
  // index) — cheaper than the reference's hash->event map lookups
  // (conservative).
  std::vector<std::vector<int32_t>> chain(n);
  std::vector<std::vector<int32_t>> round_witnesses;
  round_witnesses.reserve(1024);

  auto t0 = std::chrono::steady_clock::now();
  for (int i = 0; i < e_tot; ++i) {
    int a = pick(rng);
    int b = pick(rng);
    while (b == a) b = pick(rng);
    Ev& ev = evs[i];
    ev.creator = a;
    ev.index = idx[a]++;
    ev.self_parent = head[a];
    ev.other_parent = head[b];
    head[a] = i;

    // InitEventCoordinates (hashgraph.go:448-500)
    ev.fd.assign(n, INT32_MAX_);
    ev.la.assign(n, -1);
    const Ev* sp = ev.self_parent >= 0 ? &evs[ev.self_parent] : nullptr;
    const Ev* op = ev.other_parent >= 0 ? &evs[ev.other_parent] : nullptr;
    if (sp && op) {
      for (int k = 0; k < n; ++k)
        ev.la[k] = sp->la[k] >= op->la[k] ? sp->la[k] : op->la[k];
    } else if (sp) {
      ev.la = sp->la;
    } else if (op) {
      ev.la = op->la;
    }
    ev.fd[a] = ev.index;
    ev.la[a] = ev.index;

    // UpdateAncestorFirstDescendant (hashgraph.go:502-530): walk each
    // last-ancestor's self-parent chain until an already-set slot.
    chain[a].push_back(i);
    for (int k = 0; k < n; ++k) {
      int32_t anc_idx = ev.la[k];
      while (anc_idx >= 0) {
        Ev& anc = evs[chain[k][anc_idx]];
        if (anc.fd[a] == INT32_MAX_) {
          anc.fd[a] = ev.index;
          anc_idx -= 1;  // self-parent
        } else {
          break;
        }
      }
    }

    // Round / RoundInc (hashgraph.go:285-339): parent round, then
    // strongly-see count over the parent round's witnesses.
    int32_t parent_round = -1;
    bool is_root = !sp && !op;
    if (sp) parent_round = sp->round;
    if (op && op->round > parent_round) parent_round = op->round;
    if (is_root) {
      ev.round = 0;
    } else {
      bool inc = false;
      if (parent_round < 0) {
        inc = true;
        ev.round = parent_round + 1;
      } else {
        int c = 0;
        for (int32_t w : round_witnesses[parent_round]) {
          // stronglySee(ev, w) via coordinates (hashgraph.go:179-200)
          const Ev& wy = evs[w];
          int cnt = 0;
          for (int k = 0; k < n; ++k)
            if (ev.la[k] >= wy.fd[k]) ++cnt;
          if (cnt >= sm) ++c;
        }
        inc = c >= sm;
        ev.round = parent_round + (inc ? 1 : 0);
      }
    }
    ev.witness = !sp || ev.round > (sp ? evs[ev.self_parent].round : -1);
    if (ev.witness) {
      if ((int)round_witnesses.size() <= ev.round)
        round_witnesses.resize(ev.round + 1);
      round_witnesses[ev.round].push_back(i);
    }
  }
  auto t1 = std::chrono::steady_clock::now();
  double insert_secs = std::chrono::duration<double>(t1 - t0).count();

  // Per-sync consensus rescans (hashgraph.go:616-858), replayed at a
  // 64-event batch cadence over the same insertion order. Fame: one
  // coordinate-shortcut vote sweep per undecided round once a
  // deciding round exists (votes cached by construction — computed
  // once). RoundReceived: every batch rescans the undetermined set
  // against newly decided rounds with one O(n) compare per famous
  // witness.
  t0 = std::chrono::steady_clock::now();
  const int BATCH = 64;
  int last_round = (int)round_witnesses.size() - 1;
  std::vector<int32_t> rr(e_tot, -1);
  std::vector<int8_t> famous_done(round_witnesses.size(), 0);
  int first_undecided = 0;
  int64_t scan_ops = 0;
  for (int upto = BATCH; upto <= e_tot + BATCH - 1; upto += BATCH) {
    if (upto > e_tot) upto = e_tot;  // final partial batch
    // how deep have rounds progressed among inserted events?
    int max_round_seen = evs[upto - 1].round;
    // DecideFame: a round decides when witnesses 2+ rounds above
    // exist; each decision tallies votes from the round above via
    // strongly-see counts (coordinate compares).
    while (first_undecided + 2 <= max_round_seen) {
      int rd = first_undecided;
      for (int32_t x : round_witnesses[rd]) {
        const Ev& ex = evs[x];
        for (int32_t y : round_witnesses[rd + 1]) {
          const Ev& ey = evs[y];
          int cnt = 0;
          for (int k = 0; k < n; ++k)
            if (ey.la[k] >= ex.fd[k]) ++cnt;
          // feed the tally into an OBSERVABLE accumulator (printed
          // below) so -O3 cannot dead-code-eliminate the sweep.
          scan_ops += cnt;
        }
      }
      famous_done[rd] = 1;
      first_undecided++;
    }
    // DecideRoundReceived: every undetermined event checks the
    // decided rounds above its own round — one coordinate compare
    // per famous witness of the candidate round.
    for (int x = 0; x < upto; ++x) {
      if (rr[x] >= 0) continue;
      const Ev& ex = evs[x];
      for (int rd = ex.round + 1; rd < first_undecided; ++rd) {
        int seen = 0;
        for (int32_t wv : round_witnesses[rd]) {
          const Ev& ew = evs[wv];
          if (ew.la[ex.creator] >= ex.index) ++seen;
        }
        scan_ops += seen;
        if (2 * seen > (int)round_witnesses[rd].size()) {
          rr[x] = rd;
          break;
        }
      }
    }
  }
  auto t2 = std::chrono::steady_clock::now();
  double scan_secs = std::chrono::duration<double>(t2 - t0).count();
  double secs = insert_secs + scan_secs;
  int64_t received = 0;
  for (int x = 0; x < e_tot; ++x) received += rr[x] >= 0;
  printf("{\"n\": %d, \"events\": %d, \"wall_s\": %.3f, "
         "\"insert_s\": %.3f, \"consensus_s\": %.3f, "
         "\"events_per_s\": %.1f, \"last_round\": %d, "
         "\"received\": %lld, \"scan_checksum\": %lld}\n",
         n, e_tot, secs, insert_secs, scan_secs, e_tot / secs,
         last_round, (long long)received, (long long)scan_ops);
  return 0;
}
